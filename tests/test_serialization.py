"""jit.save/load as serialized StableHLO programs + inference Predictor."""
import numpy as np

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu.static import InputSpec


def _mlp():
    paddle_tpu.seed(0)
    return nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))


class TestSerializedProgram:
    def test_save_load_runs_without_class(self, tmp_path):
        model = _mlp()
        model.eval()
        x = paddle_tpu.to_tensor(
            np.random.RandomState(0).randn(3, 4).astype(np.float32))
        ref = model(x).numpy()
        path = str(tmp_path / "prog")
        paddle_tpu.jit.save(model, path,
                            input_spec=[InputSpec([3, 4], "float32")])
        loaded = paddle_tpu.jit.load(path)
        out = loaded(x)
        np.testing.assert_allclose(out.numpy(), ref, atol=1e-5)
        # callable without any reference to the original class
        assert type(loaded).__name__ == "TranslatedLayer"

    def test_params_only_fallback(self, tmp_path):
        model = _mlp()
        path = str(tmp_path / "params_only")
        paddle_tpu.jit.save(model, path)       # no input_spec
        sd = paddle_tpu.jit.load(path)
        assert isinstance(sd, dict) and len(sd) == 4

    def test_predictor_runs_serialized_program(self, tmp_path):
        from paddle_tpu.inference import Config, create_predictor
        model = _mlp()
        model.eval()
        x = np.random.RandomState(1).randn(3, 4).astype(np.float32)
        ref = model(paddle_tpu.to_tensor(x)).numpy()
        path = str(tmp_path / "prog2")
        paddle_tpu.jit.save(model, path,
                            input_spec=[InputSpec([3, 4], "float32")])
        config = Config(path + ".pdmodel", path + ".pdiparams")
        predictor = create_predictor(config)
        (out,) = predictor.run([x])
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_save_inference_model_roundtrip(self, tmp_path):
        from paddle_tpu.static import (load_inference_model,
                                       save_inference_model)
        model = _mlp()
        model.eval()
        path = str(tmp_path / "inf")
        save_inference_model(path, [InputSpec([2, 4], "float32")], None,
                             program=model)
        loaded = load_inference_model(path)
        x = paddle_tpu.ones([2, 4])
        np.testing.assert_allclose(loaded(x).numpy(), model(x).numpy(),
                                   atol=1e-5)


class TestPredictorCompleteness:
    """r2: real IO names, mixed-precision conversion, warmup, donation."""

    def test_artifact_is_not_pickle(self, tmp_path):
        model = _mlp()
        path = str(tmp_path / "safe")
        paddle_tpu.jit.save(model, path,
                            input_spec=[InputSpec([2, 4], "float32")])
        with open(path + ".pdmodel", "rb") as f:
            assert f.read(4) == b"PTPU"      # JSON+StableHLO container
        with open(path + ".pdiparams", "rb") as f:
            assert f.read(2) == b"PK"        # npz (zip), not pickle

    def test_io_names_from_signature(self, tmp_path):
        from paddle_tpu.inference import Config, create_predictor
        model = _mlp()
        model.eval()
        path = str(tmp_path / "named")
        paddle_tpu.jit.save(
            model, path,
            input_spec=[InputSpec([2, 4], "float32", name="feats")])
        config = Config(path + ".pdmodel", path + ".pdiparams")
        predictor = create_predictor(config)
        assert predictor.get_input_names() == ["feats"]
        h = predictor.get_input_handle("feats")
        h.copy_from_cpu(np.ones((2, 4), np.float32))
        predictor.run()
        assert predictor.get_output_names() == ["output_0"]
        out = predictor.get_output_handle("output_0").copy_to_cpu()
        assert out.shape == (2, 2)

    def test_convert_to_mixed_precision(self, tmp_path):
        import ml_dtypes
        from paddle_tpu.inference import (Config, PrecisionType,
                                          convert_to_mixed_precision,
                                          create_predictor)
        from paddle_tpu.jit.serialization import load_params_npz
        model = _mlp()
        model.eval()
        x = np.random.RandomState(2).randn(3, 4).astype(np.float32)
        ref = model(paddle_tpu.to_tensor(x)).numpy()
        path = str(tmp_path / "fp32")
        paddle_tpu.jit.save(model, path,
                            input_spec=[InputSpec([3, 4], "float32")])
        mixed = str(tmp_path / "bf16")
        convert_to_mixed_precision(
            path + ".pdmodel", path + ".pdiparams",
            mixed + ".pdmodel", mixed + ".pdiparams",
            mixed_precision=PrecisionType.Bfloat16)
        cast = load_params_npz(mixed + ".pdiparams")
        assert all(v.dtype == np.dtype(ml_dtypes.bfloat16)
                   for v in cast.values())
        predictor = create_predictor(
            Config(mixed + ".pdmodel", mixed + ".pdiparams"))
        (out,) = predictor.run([x])
        np.testing.assert_allclose(out, ref, atol=2e-2)  # bf16 storage

    def test_live_layer_warmup_and_donation(self):
        from paddle_tpu.inference import Config, create_predictor
        model = _mlp()
        config = Config()
        config.set_layer(model)
        config.enable_memory_optim()
        predictor = create_predictor(config)
        x = np.random.RandomState(3).randn(3, 4).astype(np.float32)
        predictor.warmup([x])
        (out,) = predictor.run([x])
        ref = model(paddle_tpu.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_bf16_params_only_roundtrip(self, tmp_path):
        model = _mlp()
        model.to(dtype="bfloat16") if hasattr(model, "to") else None
        path = str(tmp_path / "bf16_params")
        paddle_tpu.jit.save(model, path)
        sd = paddle_tpu.jit.load(path)
        assert len(sd) == 4


class TestReviewRegressions:
    def test_dynamic_batch_dim(self, tmp_path):
        model = _mlp()
        model.eval()
        path = str(tmp_path / "dyn")
        paddle_tpu.jit.save(model, path,
                            input_spec=[InputSpec([None, 4], "float32")])
        loaded = paddle_tpu.jit.load(path)
        for b in (1, 5, 32):
            x = paddle_tpu.ones([b, 4])
            assert tuple(loaded(x).shape) == (b, 2)

    def test_save_restores_training_mode(self, tmp_path):
        model = _mlp()
        model.train()
        paddle_tpu.jit.save(model, str(tmp_path / "t"),
                            input_spec=[InputSpec([2, 4], "float32")])
        assert model.training

    def test_softmax_explicit_dtype_wins_over_amp(self):
        from paddle_tpu import amp
        import paddle_tpu.nn.functional as F
        x = paddle_tpu.ones([2, 4], dtype="float32")
        with amp.auto_cast(dtype="bfloat16"):
            out = F.softmax(x, dtype="bfloat16")
        assert "bfloat16" in str(out.dtype)
