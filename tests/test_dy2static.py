"""Dy2Static control-flow conversion (round-4, VERDICT #2).

Reference:
python/paddle/fluid/dygraph/dygraph_to_static/convert_operators.py:108
(convert_while_loop) and :329 (convert_ifelse) — tensor-dependent
if/while/for compile under to_static; Python-valued conditions keep
eager semantics. Our lowering: tensor-if = both-branches + where select
(tape-differentiable), tensor-while = lax.while_loop (jit/dy2static.py).
"""
import numpy as np
import pytest

import paddle_tpu as p
import paddle_tpu.nn.functional as F
from paddle_tpu.jit.dy2static import convert_to_static


def _arr(*v):
    return p.to_tensor(np.array(v, np.float32))


class TestTensorIf:
    @pytest.mark.smoke
    def test_assignment_if_both_paths(self):
        @p.jit.to_static
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            else:
                y = x - 1.0
            return y

        assert np.allclose(f(_arr(1.0, 2.0)).numpy(), [2.0, 4.0])
        assert np.allclose(f(_arr(-1.0, -2.0)).numpy(), [-2.0, -3.0])
        # one compiled program serves both predicate values (select, not
        # per-branch recompilation)
        assert len(f._compiled) == 1

    def test_return_style_if(self):
        @p.jit.to_static
        def f(x):
            if x.mean() > 0:
                return x * 10.0
            else:
                return x * -1.0

        assert np.allclose(f(_arr(1.0, 2.0)).numpy(), [10.0, 20.0])
        assert np.allclose(f(_arr(-1.0, -2.0)).numpy(), [1.0, 2.0])

    def test_elif_chain(self):
        @p.jit.to_static
        def f(x):
            s = x.sum()
            if s > 10:
                y = x * 0.0
            elif s > 0:
                y = x + 1.0
            else:
                y = x - 1.0
            return y

        assert np.allclose(f(_arr(20.0)).numpy(), [0.0])
        assert np.allclose(f(_arr(3.0)).numpy(), [4.0])
        assert np.allclose(f(_arr(-3.0)).numpy(), [-4.0])

    def test_grad_flows_through_select(self):
        w = p.to_tensor(np.array([2.0], np.float32))
        w.stop_gradient = False

        @p.jit.to_static
        def step(x):
            h = x * w
            if h.sum() > 0:
                y = h * 3.0
            else:
                y = h * 5.0
            loss = y.sum()
            loss.backward()
            g = w.grad
            w.grad = None
            return loss, g

        _, g = step(_arr(1.0, 2.0))
        assert np.allclose(g.numpy(), 3.0 * 3.0)  # sum(x) * true-branch
        _, g = step(_arr(-1.0, -2.0))
        assert np.allclose(g.numpy(), 5.0 * -3.0)

    def test_python_cond_keeps_eager_semantics(self):
        def f(x, flag):
            if flag:
                return x + 1.0
            return x - 1.0

        ft = convert_to_static(f)
        assert np.allclose(ft(_arr(1.0), True).numpy(), [2.0])
        assert np.allclose(ft(_arr(1.0), False).numpy(), [0.0])

    def test_boolop_on_tensors(self):
        @p.jit.to_static
        def f(x):
            if (x.sum() > 0) and (x.max() < 10):
                return x + 100.0
            else:
                return x

        assert np.allclose(f(_arr(1.0, 2.0)).numpy(), [101.0, 102.0])
        assert np.allclose(f(_arr(50.0)).numpy(), [50.0])

    def test_single_branch_var_raises_under_trace(self):
        @p.jit.to_static
        def f(x):
            if x.sum() > 0:
                y = x * 2.0
            return y  # noqa: F821 — y unbound on the false path

        with pytest.raises(Exception, match="only one branch|assigned"):
            f(_arr(1.0))


class TestTensorWhile:
    def test_while_counts_to_sum(self):
        @p.jit.to_static
        def f(x):
            i = p.zeros([])
            while i < x.sum():
                i = i + 1.0
            return i

        assert np.allclose(f(_arr(2.5, 1.0)).numpy(), 4.0)
        assert np.allclose(f(_arr(0.2)).numpy(), 1.0)

    def test_for_over_range_tensor(self):
        @p.jit.to_static
        def f(n, x):
            acc = x * 0.0
            for _ in range(n):
                acc = acc + x
            return acc

        n = p.to_tensor(np.int32(3))
        assert np.allclose(f(n, _arr(1.0, 2.0)).numpy(), [3.0, 6.0])

    def test_python_while_unchanged(self):
        def f(x, n):
            i = 0
            while i < n:
                x = x + 1.0
                i += 1
            return x

        ft = convert_to_static(f)
        assert np.allclose(ft(_arr(0.0), 4).numpy(), [4.0])

    def test_newton_sqrt_decode_loop(self):
        # while-loop with real math in the body (Newton iteration)
        @p.jit.to_static
        def f(a):
            x = a * 0.5 + 1.0
            err = p.to_tensor(np.float32(1e9))
            while err > 1e-5:
                nx = 0.5 * (x + a / x)
                err = (nx - x).abs().max()
                x = nx
            return x

        out = f(_arr(2.0, 9.0, 16.0))
        assert np.allclose(out.numpy(), [np.sqrt(2.0), 3.0, 4.0], atol=1e-4)


class TestConvertCall:
    def test_layer_forward_converted_recursively(self):
        class Gate(p.nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = p.nn.Linear(2, 2)

            def forward(self, x):
                h = self.lin(x)
                if h.sum() > 0:
                    h = h * 2.0
                else:
                    h = h * 0.5
                return h

        net = Gate()

        @p.jit.to_static
        def step(x):
            return net(x).sum()

        x = _arr(1.0, 2.0)
        assert np.allclose(float(net(x).sum().numpy()),
                           float(step(x).numpy()), atol=1e-6)

    def test_helper_function_converted(self):
        def clip_step(x, lim):
            if x.abs().max() > lim:
                return x * 0.5
            else:
                return x

        @p.jit.to_static
        def step(x):
            return clip_step(x, 1.0)

        assert np.allclose(step(_arr(4.0)).numpy(), [2.0])
        assert np.allclose(step(_arr(0.5)).numpy(), [0.5])

    def test_training_model_with_control_flow(self):
        # end-to-end: a model whose forward branches on a tensor trains
        # under to_static and the loss decreases
        class Net(p.nn.Layer):
            def __init__(self):
                super().__init__()
                self.l1 = p.nn.Linear(4, 16)
                self.l2 = p.nn.Linear(16, 2)

            def forward(self, x):
                h = F.relu(self.l1(x))
                if h.mean() > 0.5:
                    h = h * 0.9
                else:
                    h = h * 1.1
                return self.l2(h)

        p.seed(0)
        net = Net()
        opt = p.optimizer.Adam(learning_rate=0.05,
                               parameters=net.parameters())

        @p.jit.to_static
        def train_step(x, y):
            loss = F.cross_entropy(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        rng = np.random.default_rng(0)
        x = p.to_tensor(rng.standard_normal((32, 4)).astype(np.float32))
        y = p.to_tensor((rng.standard_normal(32) > 0).astype(np.int64))
        losses = [float(train_step(x, y).numpy()) for _ in range(30)]
        assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]


class TestEdgeCases:
    """Composability battery: nested/mixed control flow, boolop chains,
    aug/tuple assignment, dict outputs, eager python semantics."""

    def test_nested_if_in_tensor_while(self):
        @p.jit.to_static
        def f(x):
            i = p.zeros([])
            acc = x * 0.0
            while i < 3.0:
                if x.sum() > 0:
                    acc = acc + x
                else:
                    acc = acc - x
                i = i + 1.0
            return acc

        assert np.allclose(f(_arr(1.0, 2.0)).numpy(), [3.0, 6.0])
        assert np.allclose(f(_arr(-1.0)).numpy(), [3.0])

    def test_if_inside_python_for(self):
        @p.jit.to_static
        def f(x):
            acc = x * 0.0
            for k in [1.0, 2.0, 3.0]:
                if (x.sum() * k) > 4.0:
                    acc = acc + k
                else:
                    acc = acc - k
            return acc

        assert np.allclose(f(_arr(1.0, 2.0)).numpy(), [4.0, 4.0])

    def test_boolop_chain_and_or_not(self):
        @p.jit.to_static
        def f(x):
            if (x.sum() > 0) and (x.max() < 10) and (x.min() > 0):
                y = x + 1.0
            elif (x.sum() > 0) or (x.min() > 100):
                y = x * 2.0
            else:
                y = x
            if not (y.sum() > 100):
                y = y + 0.5
            return y

        assert np.allclose(f(_arr(1.0, 2.0)).numpy(), [2.5, 3.5])
        assert np.allclose(f(_arr(50.0)).numpy(), [100.5])

    def test_aug_and_tuple_assignment(self):
        @p.jit.to_static
        def f(x):
            y = x * 1.0
            if x.sum() > 0:
                y += 10.0
                a, b = x + 1.0, x + 2.0
            else:
                y -= 10.0
                a, b = x - 1.0, x - 2.0
            return y + a + b

        assert np.allclose(f(_arr(1.0)).numpy(), [16.0])
        assert np.allclose(f(_arr(-1.0)).numpy(), [-16.0])

    def test_dict_branch_output(self):
        @p.jit.to_static
        def f(x):
            if x.sum() > 0:
                d = {"a": x + 1.0}
            else:
                d = {"a": x - 1.0}
            return d["a"]

        assert np.allclose(f(_arr(1.0, 2.0)).numpy(), [2.0, 3.0])
        assert np.allclose(f(_arr(-5.0)).numpy(), [-6.0])

    def test_eager_python_loop_semantics_preserved(self):
        def f(x, n):
            total = 0.0
            for i in range(n):
                if i % 2 == 0:
                    total += i
            return x * 0.0 + total

        ft = convert_to_static(f)
        assert np.allclose(ft(_arr(1.0), 5).numpy(), [6.0])
        assert np.allclose(ft(_arr(1.0), 3).numpy(), [2.0])


class TestIdentityTestRejection:
    """TL005 (PR 15 satellite): identity tests against names bound in
    only one branch of a convertible `if` are rejected at CONVERSION
    time with the variable named — the one poison-sentinel read the
    UNDEF sentinel cannot intercept (`maybe_bound is None` would
    silently evaluate False under a trace)."""

    def _raises_tl005(self, fn, name):
        from paddle_tpu.analysis.rules import TraceHazardError
        with pytest.raises(TraceHazardError) as ei:
            convert_to_static(fn)
        assert ei.value.code == "TL005"
        assert f"`{name}`" in str(ei.value)

    def test_one_branch_binding_then_is_none_rejected(self):
        def f(x):
            if (x > 0).all():
                status = x * 2
            return status is None

        self._raises_tl005(f, "status")

    def test_is_not_and_either_side_rejected(self):
        def f(x):
            if (x > 0).all():
                pass
            else:
                marker = x + 1
            return None is not marker

        self._raises_tl005(f, "marker")

    def test_bound_on_every_path_converts(self):
        def f(x):
            y = None
            if (x.sum() > 0):
                y = x * 2
            if y is None:
                return x
            return y

        ft = convert_to_static(f)
        assert np.allclose(ft(_arr(1.0)).numpy(), [2.0])
        # eager semantics for the python-valued read stay intact
        assert np.allclose(ft(_arr(-1.0)).numpy(), [-1.0])

    def test_rebind_between_if_and_test_converts(self):
        def f(x):
            if (x.sum() > 0):
                y = x * 2
            y = x + 1.0
            t = 1.0 if y is None else 0.0
            return y + t

        ft = convert_to_static(f)
        assert np.allclose(ft(_arr(1.0)).numpy(), [2.0])

    def test_identity_test_before_the_if_converts(self):
        def f(x, flag=None):
            use = flag is None
            if (x.sum() > 0):
                y = x * 2
            else:
                y = x
            return y if use else x

        ft = convert_to_static(f)
        assert np.allclose(ft(_arr(3.0)).numpy(), [6.0])

    def test_to_static_wrap_surfaces_the_error(self):
        from paddle_tpu.analysis.rules import TraceHazardError

        def f(x):
            if (x > 0).all():
                out = x + 1
            return out is not None

        with pytest.raises(TraceHazardError):
            p.jit.to_static(f)

    def test_suppression_comment_waives_tl005(self):
        # a short-circuit-guarded identity test is provably safe but
        # outside the checker's sight — the standard tracelint
        # suppression spelling waives it on that line
        def f(x, debug=False):
            if debug:
                aux = x * 2
            if debug and aux is not None:  # tracelint: disable=TL005
                return aux
            return x

        ft = convert_to_static(f)
        assert np.allclose(ft(_arr(3.0)).numpy(), [3.0])
        assert np.allclose(ft(_arr(3.0), True).numpy(), [6.0])
