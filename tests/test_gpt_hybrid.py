"""4-D hybrid GPT (dp×pp×tp×sp explicit shard_map program): the 8-device
hybrid must match the same math on a 1-device mesh — loss AND grads — and
train."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import (
    init_hybrid_gpt_params,
    make_hybrid_loss_fn,
    make_hybrid_train_step,
)


def _cfg():
    return GPTConfig(vocab_size=96, hidden_size=32, num_layers=4,
                     num_heads=4, max_seq_len=64, dropout=0.0)


def _data(mesh):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 96, (4, 32)).astype(np.int32)
    labels = rng.integers(0, 96, (4, 32)).astype(np.int32)
    sh = NamedSharding(mesh, P("dp", "sp"))
    return jax.device_put(ids, sh), jax.device_put(labels, sh)


def _host_params(params):
    return jax.tree_util.tree_map(np.asarray, params)


@pytest.fixture
def meshes():
    old = mesh_mod.get_mesh()
    yield
    mesh_mod.set_mesh(old)


def test_hybrid_matches_single_device(meshes):
    cfg = _cfg()
    mesh8 = mesh_mod.init_mesh({"dp": 1, "pp": 2, "tp": 2, "sp": 2})
    params8 = init_hybrid_gpt_params(cfg, mesh8, seed=0)
    host = _host_params(params8)

    loss8 = make_hybrid_loss_fn(cfg, mesh8, num_microbatches=2)
    ids8, labels8 = _data(mesh8)
    l8, g8 = jax.jit(jax.value_and_grad(loss8))(params8, ids8, labels8)

    mesh1 = mesh_mod.init_mesh(
        {"dp": 1, "pp": 1, "tp": 1, "sp": 1}, devices=jax.devices()[:1])
    params1 = jax.tree_util.tree_map(jnp.asarray, host)
    loss1 = make_hybrid_loss_fn(cfg, mesh1, num_microbatches=2)
    ids1, labels1 = _data(mesh1)
    l1, g1 = jax.jit(jax.value_and_grad(loss1))(params1, ids1, labels1)

    np.testing.assert_allclose(float(l8), float(l1), rtol=2e-5)
    flat8 = jax.tree_util.tree_leaves(g8)
    flat1 = jax.tree_util.tree_leaves(g1)
    for a, b in zip(flat8, flat1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-3)


def test_hybrid_trains(meshes):
    cfg = _cfg()
    mesh = mesh_mod.init_mesh({"dp": 2, "pp": 2, "tp": 2, "sp": 1})
    params = init_hybrid_gpt_params(cfg, mesh, seed=0)
    step = make_hybrid_train_step(cfg, mesh, lr=0.1, num_microbatches=2)
    ids, labels = _data(mesh)
    losses = []
    for _ in range(6):
        params, loss = step(params, ids, labels)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
