"""4-D hybrid GPT (dp×pp×tp×sp explicit shard_map program): the 8-device
hybrid must match the same math on a 1-device mesh — loss AND grads — and
train."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import (
    init_hybrid_gpt_params,
    make_hybrid_grad_fn,
    make_hybrid_loss_fn,
    make_hybrid_train_step,
)


def _cfg():
    return GPTConfig(vocab_size=96, hidden_size=32, num_layers=4,
                     num_heads=4, max_seq_len=64, dropout=0.0)


def _data(mesh):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 96, (4, 32)).astype(np.int32)
    labels = rng.integers(0, 96, (4, 32)).astype(np.int32)
    sh = NamedSharding(mesh, P("dp", "sp"))
    return jax.device_put(ids, sh), jax.device_put(labels, sh)


def _host_params(params):
    return jax.tree_util.tree_map(np.asarray, params)


@pytest.fixture
def meshes():
    old = mesh_mod.get_mesh()
    yield
    mesh_mod.set_mesh(old)


@pytest.mark.nightly  # the 1f1b + interleave parity tests below cover
# the hybrid-vs-single-device claim in the default gate run
def test_hybrid_matches_single_device(meshes):
    cfg = _cfg()
    mesh8 = mesh_mod.init_mesh({"dp": 1, "pp": 2, "tp": 2, "sp": 2})
    params8 = init_hybrid_gpt_params(cfg, mesh8, seed=0)
    host = _host_params(params8)

    loss8 = make_hybrid_loss_fn(cfg, mesh8, num_microbatches=2)
    ids8, labels8 = _data(mesh8)
    l8, g8 = jax.jit(jax.value_and_grad(loss8))(params8, ids8, labels8)

    mesh1 = mesh_mod.init_mesh(
        {"dp": 1, "pp": 1, "tp": 1, "sp": 1}, devices=jax.devices()[:1])
    params1 = jax.tree_util.tree_map(jnp.asarray, host)
    loss1 = make_hybrid_loss_fn(cfg, mesh1, num_microbatches=2)
    ids1, labels1 = _data(mesh1)
    l1, g1 = jax.jit(jax.value_and_grad(loss1))(params1, ids1, labels1)

    np.testing.assert_allclose(float(l8), float(l1), rtol=2e-5)
    flat8 = jax.tree_util.tree_leaves(g8)
    flat1 = jax.tree_util.tree_leaves(g1)
    for a, b in zip(flat8, flat1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-3)


def test_vocab_table_is_sharded_not_replicated(meshes):
    """r2 (VERDICT #3): wte must shard over tp — each tp shard holds
    V/tp rows, so no device stores the full table or full-vocab logits."""
    cfg = _cfg()
    mesh = mesh_mod.init_mesh({"dp": 1, "pp": 2, "tp": 2, "sp": 2})
    params = init_hybrid_gpt_params(cfg, mesh, seed=0)
    wte = params["wte"]
    spec = wte.sharding.spec
    assert spec[0] == "tp", f"wte vocab dim not tp-sharded: {spec}"
    for shard in wte.addressable_shards:
        assert shard.data.shape == (cfg.vocab_size // 2, cfg.hidden_size)


def test_vocab_parallel_primitives_match_dense():
    """mp_ops on a pure-tp mesh == dense embedding/CE."""
    import paddle_tpu  # noqa: F401  (conftest sets the 8-dev CPU platform)
    from paddle_tpu.distributed.fleet.mp_ops import (
        vocab_parallel_cross_entropy, vocab_parallel_embedding)

    mesh = mesh_mod.init_mesh({"tp": 8})
    rng = np.random.default_rng(1)
    V, H, B = 64, 16, 5
    table = rng.normal(0, 1, (V, H)).astype(np.float32)
    ids = rng.integers(0, V, (B,)).astype(np.int32)
    logits = rng.normal(0, 1, (B, V)).astype(np.float32)
    labels = rng.integers(0, V, (B,)).astype(np.int32)

    emb_fn = jax.shard_map(
        lambda t, i: vocab_parallel_embedding(t, i, "tp"), mesh=mesh,
        in_specs=(P("tp", None), P()), out_specs=P(), check_vma=False)
    got = np.asarray(emb_fn(table, ids))
    np.testing.assert_allclose(got, table[ids], atol=1e-6)

    ce_fn = jax.shard_map(
        lambda lg, lb: vocab_parallel_cross_entropy(lg, lb, "tp"), mesh=mesh,
        in_specs=(P(None, "tp"), P()), out_specs=P(), check_vma=False)
    got = np.asarray(ce_fn(logits, labels))
    ref = -(logits - np.log(np.exp(logits).sum(-1, keepdims=True))
            )[np.arange(B), labels]
    np.testing.assert_allclose(got, ref, rtol=1e-5)
    mesh_mod.set_mesh(None)


def test_hybrid_trains(meshes):
    cfg = _cfg()
    mesh = mesh_mod.init_mesh({"dp": 2, "pp": 2, "tp": 2, "sp": 1})
    params = init_hybrid_gpt_params(cfg, mesh, seed=0)
    step = make_hybrid_train_step(cfg, mesh, lr=0.1, num_microbatches=2)
    ids, labels = _data(mesh)
    losses = []
    for _ in range(6):
        params, loss = step(params, ids, labels)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_hybrid_1f1b_matches_single_device(meshes):
    """r3 (VERDICT #3): the flagship on the explicit 1F1B schedule — tp
    psums + sp ring attention composed with pipeline_1f1b_body — must match
    the same math on a 1-device mesh, loss AND grads."""
    from paddle_tpu.models.gpt_hybrid import make_hybrid_grad_fn

    cfg = _cfg()
    mesh8 = mesh_mod.init_mesh({"dp": 1, "pp": 2, "tp": 2, "sp": 2})
    params8 = init_hybrid_gpt_params(cfg, mesh8, seed=0)
    host = _host_params(params8)

    grad8 = make_hybrid_grad_fn(cfg, mesh8, num_microbatches=2)
    ids8, labels8 = _data(mesh8)
    l8, g8 = jax.jit(grad8)(params8, ids8, labels8)

    mesh1 = mesh_mod.init_mesh(
        {"dp": 1, "pp": 1, "tp": 1, "sp": 1}, devices=jax.devices()[:1])
    params1 = jax.tree_util.tree_map(jnp.asarray, host)
    loss1 = make_hybrid_loss_fn(cfg, mesh1, num_microbatches=2)
    ids1, labels1 = _data(mesh1)
    l1, g1 = jax.jit(jax.value_and_grad(loss1))(params1, ids1, labels1)

    np.testing.assert_allclose(float(l8), float(l1), rtol=2e-5)
    flat8 = jax.tree_util.tree_leaves(g8)
    flat1 = jax.tree_util.tree_leaves(g1)
    for a, b in zip(flat8, flat1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-3)


@pytest.mark.nightly  # schedule parity tests cover 1f1b in the gate
def test_hybrid_1f1b_train_step_decreases_loss(meshes):
    cfg = _cfg()
    mesh = mesh_mod.init_mesh({"dp": 1, "pp": 2, "tp": 2, "sp": 2})
    params = init_hybrid_gpt_params(cfg, mesh, seed=0)
    step = make_hybrid_train_step(cfg, mesh, lr=0.1, num_microbatches=2,
                                  schedule="1f1b")
    ids, labels = _data(mesh)
    losses = []
    for _ in range(4):
        params, loss = step(params, ids, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_hybrid_interleaved_matches_single_device(meshes):
    """r3 (VERDICT #3): the interleaved virtual-stage schedule (V chunks
    per device, Megatron layer assignment) must compute the same logical
    model — loss AND grads — as the plain 1-device reference."""
    from paddle_tpu.distributed.pipeline import interleave_layer_permutation

    cfg = _cfg()                      # 4 layers
    V = 2                             # pp=2 * V=2 -> 1 layer per chunk
    mesh8 = mesh_mod.init_mesh({"dp": 1, "pp": 2, "tp": 2, "sp": 2})
    params8 = init_hybrid_gpt_params(cfg, mesh8, seed=0, virtual_chunks=V)

    loss8 = make_hybrid_loss_fn(cfg, mesh8, num_microbatches=2,
                                pipeline="interleave", virtual_chunks=V)
    ids8, labels8 = _data(mesh8)
    l8, g8 = jax.jit(jax.value_and_grad(loss8))(params8, ids8, labels8)

    mesh1 = mesh_mod.init_mesh(
        {"dp": 1, "pp": 1, "tp": 1, "sp": 1}, devices=jax.devices()[:1])
    params1 = init_hybrid_gpt_params(cfg, mesh1, seed=0)   # unpermuted
    loss1 = make_hybrid_loss_fn(cfg, mesh1, num_microbatches=2)
    ids1, labels1 = _data(mesh1)
    l1, g1 = jax.jit(jax.value_and_grad(loss1))(params1, ids1, labels1)

    np.testing.assert_allclose(float(l8), float(l1), rtol=2e-5)

    # stage grads come back in the interleaved storage layout; invert the
    # permutation before comparing against the sequential reference
    perm = interleave_layer_permutation(cfg.num_layers, 2, V)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm))
    for k in g8["stages"]:
        got = np.asarray(g8["stages"][k])[inv]
        want = np.asarray(g1["stages"][k])
        np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)
    for k in ("wte", "wpe", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(np.asarray(g8[k]), np.asarray(g1[k]),
                                   atol=2e-4, rtol=2e-3)


@pytest.mark.parametrize(
    "V,num_layers",
    [pytest.param(2, 4, id="V2"),
     pytest.param(4, 8, id="V4", marks=pytest.mark.nightly)])
def test_hybrid_interleaved_1f1b_matches_single_device(meshes, V,
                                                       num_layers):
    """r4 (VERDICT #5): the INTERLEAVED 1F1B schedule — V virtual chunks
    per device composed with the explicit per-tick fwd/bwd
    (pipeline_1f1b_interleaved_body) — must match the 1-device reference
    on loss and every grad leaf, at both virtual-stage ratios. This is
    the schedule where the bubble/V win and the 1F1B activation-memory
    bound hold TOGETHER (the actual semantics of the reference's
    PipelineParallelWithInterleave, pipeline_parallel.py:461)."""
    from paddle_tpu.distributed.pipeline import interleave_layer_permutation

    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=num_layers,
                    num_heads=4, max_seq_len=64, dropout=0.0)
    mesh8 = mesh_mod.init_mesh({"dp": 1, "pp": 2, "tp": 2, "sp": 2})
    params8 = init_hybrid_gpt_params(cfg, mesh8, seed=0, virtual_chunks=V)
    grad8 = make_hybrid_grad_fn(cfg, mesh8, num_microbatches=4,
                                virtual_chunks=V)
    ids8, labels8 = _data(mesh8)
    l8, g8 = jax.jit(grad8)(params8, ids8, labels8)

    mesh1 = mesh_mod.init_mesh(
        {"dp": 1, "pp": 1, "tp": 1, "sp": 1}, devices=jax.devices()[:1])
    params1 = init_hybrid_gpt_params(cfg, mesh1, seed=0)
    loss1 = make_hybrid_loss_fn(cfg, mesh1, num_microbatches=4)
    ids1, labels1 = _data(mesh1)
    l1, g1 = jax.jit(jax.value_and_grad(loss1))(params1, ids1, labels1)

    np.testing.assert_allclose(float(l8), float(l1), rtol=2e-5)
    perm = interleave_layer_permutation(cfg.num_layers, 2, V)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm))
    for k in g8["stages"]:
        np.testing.assert_allclose(
            np.asarray(g8["stages"][k])[inv],
            np.asarray(g1["stages"][k]), atol=2e-4, rtol=2e-3, err_msg=k)
    for k in ("wte", "wpe", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(np.asarray(g8[k]), np.asarray(g1[k]),
                                   atol=2e-4, rtol=2e-3, err_msg=k)


@pytest.mark.nightly  # schedule parity tests cover interleave in the gate
def test_hybrid_interleaved_train_step(meshes):
    cfg = _cfg()
    mesh = mesh_mod.init_mesh({"dp": 1, "pp": 2, "tp": 2, "sp": 2})
    params = init_hybrid_gpt_params(cfg, mesh, seed=0, virtual_chunks=2)
    step = make_hybrid_train_step(cfg, mesh, lr=0.1, num_microbatches=2,
                                  schedule="interleave", virtual_chunks=2)
    ids, labels = _data(mesh)
    losses = []
    for _ in range(3):
        params, loss = step(params, ids, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def _moe_cfg(num_layers=4):
    return GPTConfig(vocab_size=96, hidden_size=32, num_layers=num_layers,
                     num_heads=4, max_seq_len=64, dropout=0.0,
                     moe_num_experts=4, moe_top_k=2,
                     moe_capacity_factor=(64.0, 64.0))


def test_hybrid_moe_5axis_matches_single_device(meshes):
    """The FULL 5-axis composition (dp x pp x tp x sp x ep) in one
    shard_map program: GShard expert FFNs (grouped per-ep-rank dispatch,
    one all_to_all pair) composed with the Megatron tp psums + pipeline,
    on BOTH the outer-AD GPipe path and the explicit 1F1B schedule —
    loss AND all grads must match the same math on one device."""
    from paddle_tpu.models.gpt_hybrid import make_hybrid_grad_fn

    cfg = _moe_cfg()
    mesh8 = mesh_mod.init_mesh({"dp": 1, "pp": 2, "tp": 2, "sp": 1,
                                "ep": 2})
    params8 = init_hybrid_gpt_params(cfg, mesh8, seed=0)
    host = _host_params(params8)
    ids8, labels8 = _data(mesh8)
    l8g, g8g = jax.jit(jax.value_and_grad(
        make_hybrid_loss_fn(cfg, mesh8, 2)))(params8, ids8, labels8)
    l8f, g8f = jax.jit(make_hybrid_grad_fn(cfg, mesh8, 2))(
        params8, ids8, labels8)

    cfg1 = _moe_cfg()
    mesh1 = mesh_mod.init_mesh(
        {"dp": 1, "pp": 1, "tp": 1, "sp": 1, "ep": 1},
        devices=jax.devices()[:1])
    params1 = jax.tree_util.tree_map(jnp.asarray, host)
    ids1, labels1 = _data(mesh1)
    l1, g1 = jax.jit(jax.value_and_grad(
        make_hybrid_loss_fn(cfg1, mesh1, 2)))(params1, ids1, labels1)

    np.testing.assert_allclose(float(l8g), float(l1), rtol=2e-5)
    np.testing.assert_allclose(float(l8f), float(l1), rtol=2e-5)
    for g8 in (g8g, g8f):
        for a, b in zip(jax.tree_util.tree_leaves(g8),
                        jax.tree_util.tree_leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-3)


def test_hybrid_moe_with_dp_sp_groups(meshes):
    """dp2 x sp2 x ep2 (pp1 tp1): distinct token groups per device — the
    ('dp','sp') psum of ep-sharded expert grads and per-group routing
    must still reproduce single-device math (ample capacity keeps
    routing decisions token-independent)."""
    cfg = _moe_cfg(num_layers=2)
    mesh8 = mesh_mod.init_mesh({"dp": 2, "pp": 1, "tp": 1, "sp": 2,
                                "ep": 2})
    params8 = init_hybrid_gpt_params(cfg, mesh8, seed=0)
    host = _host_params(params8)
    ids8, labels8 = _data(mesh8)
    l8, g8 = jax.jit(jax.value_and_grad(
        make_hybrid_loss_fn(cfg, mesh8, 2)))(params8, ids8, labels8)

    cfg1 = _moe_cfg(num_layers=2)
    mesh1 = mesh_mod.init_mesh(
        {"dp": 1, "pp": 1, "tp": 1, "sp": 1, "ep": 1},
        devices=jax.devices()[:1])
    params1 = jax.tree_util.tree_map(jnp.asarray, host)
    ids1, labels1 = _data(mesh1)
    l1, g1 = jax.jit(jax.value_and_grad(
        make_hybrid_loss_fn(cfg1, mesh1, 2)))(params1, ids1, labels1)
    np.testing.assert_allclose(float(l8), float(l1), rtol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(g8),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-3)


def test_hybrid_moe_trains_with_capacity_drops(meshes):
    """Modest capacity factor (tokens actually drop) on the 1F1B
    schedule: training must still make progress — exercises the
    pos<capacity drop path the ample-capacity parity tests bypass."""
    from paddle_tpu.models.gpt_hybrid import make_hybrid_train_step

    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=64, dropout=0.0,
                    moe_num_experts=4, moe_top_k=2,
                    moe_capacity_factor=(1.0, 1.0))
    mesh = mesh_mod.init_mesh({"dp": 1, "pp": 2, "tp": 2, "sp": 1,
                               "ep": 2})
    params = init_hybrid_gpt_params(cfg, mesh, seed=0)
    step = make_hybrid_train_step(cfg, mesh, lr=0.1, num_microbatches=2,
                                  schedule="1f1b")
    ids, labels = _data(mesh)
    losses = []
    for _ in range(4):
        params, loss = step(params, ids, labels)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


@pytest.mark.nightly  # the 1f1b + gpipe MoE parities run in the gate;
# this confirms the interleaved virtual-stage schedule composes with the
# expert banks too (stage-tree reshape carries the [L, E, ...] leaves)
def test_hybrid_moe_interleaved_matches_single_device(meshes):
    cfg = _moe_cfg()
    V = 2
    mesh8 = mesh_mod.init_mesh({"dp": 1, "pp": 2, "tp": 2, "sp": 1,
                                "ep": 2})
    params8 = init_hybrid_gpt_params(cfg, mesh8, seed=0, virtual_chunks=V)
    ids8, labels8 = _data(mesh8)
    l8, g8 = jax.jit(jax.value_and_grad(make_hybrid_loss_fn(
        cfg, mesh8, 2, pipeline="interleave", virtual_chunks=V)))(
        params8, ids8, labels8)

    cfg1 = _moe_cfg()
    mesh1 = mesh_mod.init_mesh(
        {"dp": 1, "pp": 1, "tp": 1, "sp": 1, "ep": 1},
        devices=jax.devices()[:1])
    params1 = init_hybrid_gpt_params(cfg1, mesh1, seed=0)
    ids1, labels1 = _data(mesh1)
    l1, g1 = jax.jit(jax.value_and_grad(make_hybrid_loss_fn(
        cfg1, mesh1, 2)))(params1, ids1, labels1)
    np.testing.assert_allclose(float(l8), float(l1), rtol=2e-5)
    # grads too, mapped back through the interleave layer permutation
    from paddle_tpu.distributed.pipeline import interleave_layer_permutation
    perm = interleave_layer_permutation(cfg.num_layers, 2, V)
    inv = np.argsort(perm)
    for key, a in g8["stages"].items():
        b = np.asarray(g1["stages"][key])
        np.testing.assert_allclose(np.asarray(a)[inv], b,
                                   atol=2e-4, rtol=2e-3)
