"""Worker script for the real multi-process collective test.

Launched (twice, as separate OS processes) by
tests/test_distributed_multiprocess.py through
``python -m paddle_tpu.distributed.launch --master ... --nnodes 2
--rank R`` — so by the time this runs, ``launch()`` has already called
``jax.distributed.initialize`` against the coordinator and installed
the global mesh.  The worker proves the multi-host path end to end:

- ``jax.process_count() == 2`` (real DCN-style bootstrap, not a
  single-process virtual mesh);
- a ``paddle_tpu.distributed.all_reduce`` across the two processes
  produces the cross-process sum on BOTH ranks (the eager multi-host
  path: ``multihost_utils.process_allgather`` + reduce).

Results are written as one JSON file per rank (argv[1] is the output
directory); the parent asserts on them — a crashed or wedged worker
simply never writes its file.
"""
import json
import os
import sys

import numpy as np


def main():
    out_dir = sys.argv[1]
    import jax

    import paddle_tpu as P
    from paddle_tpu import distributed as dist
    from paddle_tpu.analysis import kv_tracer

    kv_tracer.arm_from_env()   # no-op unless PTPU_KV_TRACE_DIR is set
    rank = jax.process_index()
    from paddle_tpu.observability import fleettrace
    fleettrace.arm_from_env(rank=rank)    # needs PTPU_OBS_SPOOL_DIR
    nprocs = jax.process_count()

    t = P.to_tensor(np.array([float(rank + 1), 10.0 * (rank + 1)],
                             np.float32))
    dist.all_reduce(t)                       # SUM over processes
    reduced = [float(x) for x in np.asarray(t.numpy())]

    gathered = []
    dist.all_gather(gathered,
                    P.to_tensor(np.array([rank], np.int32)))
    ranks_seen = sorted(int(np.asarray(g.numpy())[0]) for g in gathered)

    b = P.to_tensor(np.array([100.0 + rank], np.float32))
    dist.broadcast(b, src=1)                 # rank 1's value everywhere
    broadcast_val = float(np.asarray(b.numpy())[0])

    payload = {
        "rank": rank,
        "nprocs": nprocs,
        "reduced": reduced,
        "ranks_seen": ranks_seen,
        "broadcast": broadcast_val,
    }
    path = os.path.join(out_dir, f"rank{rank}.json")
    with open(path + ".tmp", "w") as fh:
        json.dump(payload, fh)
    os.replace(path + ".tmp", path)


if __name__ == "__main__":
    main()
