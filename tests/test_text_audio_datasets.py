"""text.datasets / audio.datasets / audio wave backend.

Reference: python/paddle/text/datasets/, python/paddle/audio/datasets/,
python/paddle/audio/backends/wave_backend.py.
"""
import numpy as np

import paddle_tpu as P
from paddle_tpu.text import datasets as tds


class TestTextDatasets:
    def test_imdb_shapes_and_learnability_signal(self):
        ds = tds.Imdb(mode="train", cutoff=150)
        doc, label = ds[0]
        assert doc.dtype == np.int64 and doc.ndim == 1
        assert label.shape == (1,)
        assert doc.max() < 150
        # class-conditional token distributions must differ (learnable)
        pos = np.concatenate([ds[i][0] for i in range(len(ds))
                              if ds[i][1][0] == 1])
        neg = np.concatenate([ds[i][0] for i in range(len(ds))
                              if ds[i][1][0] == 0])
        assert abs(pos.mean() - neg.mean()) > 5

    def test_imikolov_ngram_and_seq(self):
        ds = tds.Imikolov(data_type="NGRAM", window_size=5)
        assert len(ds[0]) == 5
        ds2 = tds.Imikolov(data_type="SEQ")
        src, trg = ds2[0]
        assert src.shape == trg.shape

    def test_uci_housing_regression_learns(self):
        ds = tds.UCIHousing(mode="train")
        x, y = ds[0]
        assert x.shape == (13,) and y.shape == (1,)
        xs = np.stack([ds[i][0] for i in range(len(ds))])
        ys = np.stack([ds[i][1] for i in range(len(ds))])[:, 0]
        w, *_ = np.linalg.lstsq(xs, ys, rcond=None)
        resid = ys - xs @ w
        assert resid.var() < 0.05 * ys.var()  # linear structure present

    def test_movielens_tuple_layout(self):
        ds = tds.Movielens(mode="train")
        item = ds[0]
        assert len(item) == 8
        assert 1.0 <= float(item[-1][0]) <= 5.0

    def test_conll05_aligned_lengths(self):
        ds = tds.Conll05()
        item = ds[0]
        assert len(item) == 9
        lens = {len(part) for part in item}
        assert len(lens) == 1

    def test_wmt_translation_framing(self):
        ds = tds.WMT14(mode="train")
        src, trg, trg_next = ds[0]
        assert trg[0] == ds.BOS
        assert trg_next[-1] == ds.EOS
        np.testing.assert_array_equal(trg[1:], trg_next[:-1])
        d = ds.get_dict()
        assert len(d) == ds.dict_size
        tds.WMT16(mode="test")  # constructible


class TestAudioBackend:
    def test_wav_save_load_roundtrip(self, tmp_path):
        sr = 8000
        t = np.arange(sr // 4, dtype=np.float32) / sr
        wav = (0.3 * np.sin(2 * np.pi * 440 * t)).astype(np.float32)[None]
        path = tmp_path / "t.wav"
        P.audio.save(str(path), P.to_tensor(wav), sr)
        meta = P.audio.info(str(path))
        assert meta.sample_rate == sr
        assert meta.num_channels == 1
        back, sr2 = P.audio.load(str(path))
        assert sr2 == sr
        np.testing.assert_allclose(back.numpy(), wav, atol=2e-4)

    def test_load_frame_window(self, tmp_path):
        sr = 8000
        wav = np.linspace(-0.5, 0.5, sr, dtype=np.float32)[None]
        path = tmp_path / "w.wav"
        P.audio.save(str(path), P.to_tensor(wav), sr)
        part, _ = P.audio.load(str(path), frame_offset=100, num_frames=50)
        assert part.shape == [1, 50]
        np.testing.assert_allclose(part.numpy()[0], wav[0, 100:150],
                                   atol=2e-4)


class TestAudioDatasets:
    def test_tess_raw_and_melspectrogram(self):
        ds = P.audio.datasets.TESS(mode="train", feat_type="raw")
        wav, label = ds[0]
        assert wav.dtype == np.float32 and wav.ndim == 1
        assert 0 <= int(label) < 7
        assert np.abs(wav).max() <= 0.5 + 1e-6
        ds2 = P.audio.datasets.TESS(mode="dev", feat_type="melspectrogram",
                                    n_fft=256, hop_length=128, n_mels=32)
        feat, _ = ds2[1]
        assert feat.ndim == 2 and feat.shape[0] == 32

    def test_classes_are_spectrally_distinct(self):
        ds = P.audio.datasets.ESC50(mode="test")
        w0, l0 = ds[0]
        w1, l1 = ds[1]
        assert int(l0) != int(l1)
        # different fundamentals -> dominant FFT bins differ
        b0 = np.abs(np.fft.rfft(w0)).argmax()
        b1 = np.abs(np.fft.rfft(w1)).argmax()
        assert b0 != b1
