"""auto_parallel API on the 8-virtual-device mesh: ProcessMesh honors
process_ids, shard_tensor handles both spec forms, shard_op pins island
boundaries, reshard moves placements, Engine trains."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed import auto_parallel as ap
from paddle_tpu.distributed.mesh import set_mesh


@pytest.fixture(autouse=True)
def _fresh_mesh():
    set_mesh(None)
    yield
    set_mesh(None)


class TestProcessMesh:
    def test_process_ids_select_devices(self):
        pm = ap.ProcessMesh(shape=[2, 2], process_ids=[4, 5, 6, 7],
                            dim_names=["x", "y"])
        jm = pm.to_jax()
        got = [d.id for d in jm.devices.reshape(-1)]
        assert got == [4, 5, 6, 7]
        assert jm.axis_names == ("x", "y")

    def test_submesh_and_eq(self):
        pm = ap.ProcessMesh(mesh=[[0, 1], [2, 3]], dim_names=["dp", "tp"])
        sub = pm.get_mesh_with_dim("tp", 0)
        assert sub.process_ids == [0, 1]
        assert pm == ap.ProcessMesh(mesh=[[0, 1], [2, 3]],
                                    dim_names=["dp", "tp"])
        assert pm != ap.ProcessMesh(mesh=[[0, 1], [2, 3]],
                                    dim_names=["a", "b"])


class TestShardTensorAndReshard:
    def test_placements_form(self):
        pm = ap.ProcessMesh(shape=[4, 2], dim_names=["dp", "tp"],
                            process_ids=list(range(8)))
        x = paddle.to_tensor(np.arange(32, dtype=np.float32).reshape(8, 4))
        ap.shard_tensor(x, mesh=pm,
                        placements=[ap.Shard(0), ap.Replicate()])
        spec = x._value.sharding.spec
        assert spec[0] == "dp"

    def test_reshard_moves(self):
        pm = ap.ProcessMesh(shape=[8], dim_names=["dp"],
                            process_ids=list(range(8)))
        x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(8, 2))
        ap.shard_tensor(x, process_mesh=pm, shard_spec=["dp", None])
        assert x._value.sharding.spec[0] == "dp"
        ap.reshard(x, process_mesh=pm, shard_spec=[None, None])
        assert all(e is None for e in x._value.sharding.spec)
        np.testing.assert_allclose(
            x.numpy(), np.arange(16, dtype=np.float32).reshape(8, 2))

    def test_shard_op_pins_boundaries(self):
        pm = ap.ProcessMesh(shape=[8], dim_names=["dp"],
                            process_ids=list(range(8)))
        ap.shard_tensor(paddle.to_tensor(np.zeros(8, np.float32)),
                        process_mesh=pm, shard_spec=["dp"])  # install mesh

        def op(a, b):
            return a.matmul(b)

        sharded = ap.shard_op(op, process_mesh=pm,
                              in_shard_specs=[["dp", None], None],
                              out_shard_specs=[["dp", None]])
        a = paddle.to_tensor(
            np.random.default_rng(0).standard_normal((8, 4))
            .astype(np.float32))
        b = paddle.to_tensor(
            np.random.default_rng(1).standard_normal((4, 3))
            .astype(np.float32))
        out = sharded(a, b)
        np.testing.assert_allclose(out.numpy(), a.numpy() @ b.numpy(),
                                   rtol=1e-5, atol=1e-6)


def test_engine_trains_on_mesh():
    pm = ap.ProcessMesh(shape=[8], dim_names=["dp"],
                        process_ids=list(range(8)))
    paddle.seed(0)
    model = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                                 paddle.nn.Linear(16, 1))
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
    eng = ap.Engine(model, paddle.nn.functional.mse_loss, opt)
    eng.prepare(mesh=pm)
    rng = np.random.default_rng(0)
    X = rng.standard_normal((16, 8)).astype(np.float32)
    Y = (X.sum(1, keepdims=True) * 0.3).astype(np.float32)
    data = [(paddle.to_tensor(X), paddle.to_tensor(Y))] * 5
    hist = eng.fit(data, epochs=4)
    assert hist[-1] < hist[0]
    assert eng.evaluate(data[:1]) <= hist[0]
