"""ONNX export: jaxpr -> onnx protobuf, round-trip-verified through the
bundled numpy runtime (no onnxruntime in this image)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.onnx import export, numpy_runtime


def _roundtrip(layer, inputs, tmp_path, rtol=1e-4, atol=1e-5):
    path = export(layer, str(tmp_path / "model"), input_spec=[
        paddle.to_tensor(i) for i in inputs])
    layer.eval()
    want = layer(*[paddle.to_tensor(i) for i in inputs])
    wants = want if isinstance(want, (tuple, list)) else [want]
    got = numpy_runtime.run(path, [np.asarray(i) for i in inputs])
    for g, w in zip(got, wants):
        np.testing.assert_allclose(g, w.numpy(), rtol=rtol, atol=atol)
    return path


def test_mlp_roundtrip(tmp_path):
    paddle.seed(0)
    mlp = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                        nn.Softmax())
    x = np.random.default_rng(0).standard_normal((3, 8)).astype(np.float32)
    _roundtrip(mlp, [x], tmp_path)


def test_lenet_roundtrip(tmp_path):
    from paddle_tpu.vision.models import LeNet
    paddle.seed(1)
    model = LeNet(num_classes=10)
    x = np.random.default_rng(1).standard_normal(
        (2, 1, 28, 28)).astype(np.float32)
    _roundtrip(model, [x], tmp_path)


@pytest.mark.nightly  # conv/BN/residual ONNX ops stay covered by LeNet
def test_resnet18_roundtrip(tmp_path):
    from paddle_tpu.vision.models import resnet18
    paddle.seed(2)
    model = resnet18(num_classes=5)
    x = np.random.default_rng(2).standard_normal(
        (1, 3, 32, 32)).astype(np.float32)
    _roundtrip(model, [x], tmp_path, rtol=1e-3, atol=1e-4)


def test_embedding_and_layernorm_roundtrip(tmp_path):
    paddle.seed(3)

    class TokenMLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(32, 16)
            self.ln = nn.LayerNorm(16)
            self.fc = nn.Linear(16, 4)

        def forward(self, ids):
            return self.fc(self.ln(self.emb(ids)))

    ids = np.random.default_rng(3).integers(0, 32, (2, 7)).astype(np.int32)
    _roundtrip(TokenMLP(), [ids], tmp_path)


def test_model_proto_structure(tmp_path):
    from paddle_tpu.onnx import onnx_pb2 as pb
    mlp = nn.Sequential(nn.Linear(4, 2))
    x = np.zeros((1, 4), np.float32)
    path = export(mlp, str(tmp_path / "m"), input_spec=[
        paddle.to_tensor(x)])
    m = pb.ModelProto()
    m.ParseFromString(open(path, "rb").read())
    assert m.ir_version == 7
    assert m.opset_import[0].version == 12
    assert len(m.graph.input) == 1
    assert len(m.graph.output) == 1
    ops = {n.op_type for n in m.graph.node}
    assert "Einsum" in ops or "MatMul" in ops
