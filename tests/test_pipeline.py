"""SPMD pipeline parallelism: pipeline over `pp` mesh axis must equal
running the stages sequentially (forward AND grads) — SURVEY §4 'PP ==
no-PP'."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.pipeline import (
    microbatch,
    pipeline_forward,
    stack_stage_params,
    unmicrobatch,
    unstack_stage_params,
)

N_STAGES = 4
D = 8


def stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def make_stages(seed=0):
    rng = np.random.RandomState(seed)
    return [{"w": jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.5),
             "b": jnp.asarray(rng.randn(D).astype(np.float32) * 0.1)}
            for _ in range(N_STAGES)]


def sequential(stages, x):
    for p in stages:
        x = stage_fn(p, x)
    return x


@pytest.fixture(scope="module")
def pp_mesh():
    old = mesh_mod.get_mesh()
    mesh = mesh_mod.init_mesh({"dp": 2, "pp": N_STAGES})
    yield mesh
    mesh_mod.set_mesh(old)


def test_pipeline_matches_sequential(pp_mesh):
    stages = make_stages()
    stacked = stack_stage_params(stages)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(16, D).astype(np.float32))
    xm = microbatch(x, 8)
    out = unmicrobatch(pipeline_forward(stage_fn, stacked, xm))
    ref = sequential(stages, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_grads_match_sequential(pp_mesh):
    stages = make_stages()
    stacked = stack_stage_params(stages)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(8, D).astype(np.float32))

    def loss_pp(p, x):
        return jnp.sum(pipeline_forward(stage_fn, p, microbatch(x, 4)) ** 2)

    def loss_seq(ps, x):
        return jnp.sum(sequential(ps, x) ** 2)

    g_pp = jax.grad(loss_pp)(stacked, x)
    g_seq = jax.grad(loss_seq)(stages, x)
    g_seq_stacked = stack_stage_params(g_seq)
    for a, b in zip(jax.tree_util.tree_leaves(g_pp),
                    jax.tree_util.tree_leaves(g_seq_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_pipeline_under_jit(pp_mesh):
    stages = make_stages()
    stacked = stack_stage_params(stages)
    x = jnp.ones((8, D), jnp.float32)

    @jax.jit
    def f(p, xm):
        return pipeline_forward(stage_fn, p, xm)

    out = unmicrobatch(f(stacked, microbatch(x, 4)))
    ref = sequential(stages, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


class Test1F1B:
    """r2 (VERDICT #4): explicit 1F1B schedule — loss, grads, and schedule
    order must all match the FThenB/sequential reference."""

    @staticmethod
    def _loss_fn(lp, y, aux):
        return jnp.sum((y @ lp["head"] - aux) ** 2)

    def _run_1f1b(self, mesh, M=8):
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.distributed.pipeline import pipeline_1f1b_fn
        stages = make_stages()
        stacked = stack_stage_params(stages)
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(M * 2, D).astype(np.float32))
        aux = jnp.asarray(rng.randn(M * 2, D).astype(np.float32))
        lp = {"head": jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.3)}
        body = pipeline_1f1b_fn(stage_fn, self._loss_fn, axis_size=N_STAGES)
        pspec = jax.tree_util.tree_map(
            lambda p: P("pp", *([None] * (p.ndim - 1))), stacked)
        f = jax.jit(jax.shard_map(
            body, mesh=mesh,
            in_specs=(pspec, P(), P(), P()),
            out_specs=(P(), pspec, P(), P()), check_vma=False))
        loss, sg, gl, dx = f(stacked, lp, microbatch(x, M),
                             microbatch(aux, M))
        return stages, lp, x, aux, loss, sg, gl, dx

    def test_1f1b_matches_sequential(self, pp_mesh):
        stages, lp, x, aux, loss, sg, gl, dx = self._run_1f1b(pp_mesh)

        def ref_loss(ps, lp, x):
            return jnp.sum((sequential(ps, x) @ lp["head"] - aux) ** 2)

        ref = ref_loss(stages, lp, x)
        np.testing.assert_allclose(float(loss), float(ref), rtol=1e-4)
        g_ps, g_lp, g_x = jax.grad(ref_loss, argnums=(0, 1, 2))(
            stages, lp, x)
        for a, b in zip(jax.tree_util.tree_leaves(sg),
                        jax.tree_util.tree_leaves(
                            stack_stage_params(g_ps))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-3)
        np.testing.assert_allclose(np.asarray(gl["head"]),
                                   np.asarray(g_lp["head"]),
                                   atol=1e-4, rtol=1e-3)
        np.testing.assert_allclose(np.asarray(unmicrobatch(dx)),
                                   np.asarray(g_x), atol=1e-4, rtol=1e-3)

    def test_ring_buffer_smaller_than_stream(self, pp_mesh):
        """M=16 > R=2*pp-1=7: grads stay exact => slots are recycled at the
        1F1B cadence (FThenB ordering would corrupt them)."""
        stages, lp, x, aux, loss, sg, gl, dx = self._run_1f1b(pp_mesh, M=16)

        def ref_loss(ps):
            return jnp.sum((sequential(ps, x) @ lp["head"] - aux) ** 2)

        g_ps = jax.grad(ref_loss)(stages)
        for a, b in zip(jax.tree_util.tree_leaves(sg),
                        jax.tree_util.tree_leaves(
                            stack_stage_params(g_ps))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-3)

    def test_schedule_order(self):
        from paddle_tpu.distributed.pipeline import schedule_1f1b
        M, n = 8, 4
        sched = schedule_1f1b(M, n)
        for s in range(n):
            fwd_ticks = {m: t for t, op, m in sched[s] if op == "F"}
            bwd_ticks = {m: t for t, op, m in sched[s] if op == "B"}
            assert len(fwd_ticks) == M and len(bwd_ticks) == M
            # every microbatch goes forward before backward, on every stage
            for m in range(M):
                assert fwd_ticks[m] < bwd_ticks[m] or (
                    s == n - 1 and fwd_ticks[m] == bwd_ticks[m])
            # in-flight bound: never more than 2*(n-1)+1 outstanding
            ticks = sorted({t for t, _, _ in sched[s]})
            for t in ticks:
                inflight = sum(1 for m in range(M)
                               if fwd_ticks[m] <= t and bwd_ticks[m] > t)
                assert inflight <= 2 * (n - 1) + 1
        # last stage closes each microbatch the tick it arrives (1F1B's
        # defining property — backward starts immediately)
        last = sched[n - 1]
        f = {m: t for t, op, m in last if op == "F"}
        b = {m: t for t, op, m in last if op == "B"}
        assert all(f[m] == b[m] for m in range(M))
        # steady state on stage 0 alternates F and B within each tick pair
        mid = [e for e in sched[0] if 2 * (n - 1) <= e[0] < M]
        assert any(op == "B" for _, op, _ in mid) and \
            any(op == "F" for _, op, _ in mid)


def test_stack_unstack_roundtrip():
    stages = make_stages()
    back = unstack_stage_params(stack_stage_params(stages), N_STAGES)
    for a, b in zip(stages, back):
        np.testing.assert_array_equal(np.asarray(a["w"]), np.asarray(b["w"]))


def test_microbatch_roundtrip():
    x = jnp.arange(24, dtype=jnp.float32).reshape(12, 2)
    np.testing.assert_array_equal(
        np.asarray(unmicrobatch(microbatch(x, 4))), np.asarray(x))
    with pytest.raises(ValueError):
        microbatch(x, 5)
