"""SPMD pipeline parallelism: pipeline over `pp` mesh axis must equal
running the stages sequentially (forward AND grads) — SURVEY §4 'PP ==
no-PP'."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.pipeline import (
    microbatch,
    pipeline_forward,
    stack_stage_params,
    unmicrobatch,
    unstack_stage_params,
)

N_STAGES = 4
D = 8


def stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def make_stages(seed=0):
    rng = np.random.RandomState(seed)
    return [{"w": jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.5),
             "b": jnp.asarray(rng.randn(D).astype(np.float32) * 0.1)}
            for _ in range(N_STAGES)]


def sequential(stages, x):
    for p in stages:
        x = stage_fn(p, x)
    return x


@pytest.fixture(scope="module")
def pp_mesh():
    old = mesh_mod.get_mesh()
    mesh = mesh_mod.init_mesh({"dp": 2, "pp": N_STAGES})
    yield mesh
    mesh_mod.set_mesh(old)


def test_pipeline_matches_sequential(pp_mesh):
    stages = make_stages()
    stacked = stack_stage_params(stages)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(16, D).astype(np.float32))
    xm = microbatch(x, 8)
    out = unmicrobatch(pipeline_forward(stage_fn, stacked, xm))
    ref = sequential(stages, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_grads_match_sequential(pp_mesh):
    stages = make_stages()
    stacked = stack_stage_params(stages)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(8, D).astype(np.float32))

    def loss_pp(p, x):
        return jnp.sum(pipeline_forward(stage_fn, p, microbatch(x, 4)) ** 2)

    def loss_seq(ps, x):
        return jnp.sum(sequential(ps, x) ** 2)

    g_pp = jax.grad(loss_pp)(stacked, x)
    g_seq = jax.grad(loss_seq)(stages, x)
    g_seq_stacked = stack_stage_params(g_seq)
    for a, b in zip(jax.tree_util.tree_leaves(g_pp),
                    jax.tree_util.tree_leaves(g_seq_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_pipeline_under_jit(pp_mesh):
    stages = make_stages()
    stacked = stack_stage_params(stages)
    x = jnp.ones((8, D), jnp.float32)

    @jax.jit
    def f(p, xm):
        return pipeline_forward(stage_fn, p, xm)

    out = unmicrobatch(f(stacked, microbatch(x, 4)))
    ref = sequential(stages, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_stack_unstack_roundtrip():
    stages = make_stages()
    back = unstack_stage_params(stack_stage_params(stages), N_STAGES)
    for a, b in zip(stages, back):
        np.testing.assert_array_equal(np.asarray(a["w"]), np.asarray(b["w"]))


def test_microbatch_roundtrip():
    x = jnp.arange(24, dtype=jnp.float32).reshape(12, 2)
    np.testing.assert_array_equal(
        np.asarray(unmicrobatch(microbatch(x, 4))), np.asarray(x))
    with pytest.raises(ValueError):
        microbatch(x, 5)
