"""Test env: 8 virtual CPU devices (multi-chip sharding tests run here).

Must set the env BEFORE jax initializes its backends (backend selection is
lazy — first jax.devices() call wins).
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as P
    P.seed(0)
    np.random.seed(0)
    yield
