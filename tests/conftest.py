"""Test env: 8 virtual CPU devices (multi-chip sharding tests run here).

Must set the env BEFORE jax initializes its backends (backend selection is
lazy — first jax.devices() call wins).
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "backend_optimization_level" not in flags:
    # tests are compile-bound, not run-bound: XLA:CPU at -O0 halves the
    # compile time of the deep-model tests with no semantic change
    flags = (flags + " --xla_backend_optimization_level=0").strip()
os.environ["XLA_FLAGS"] = flags

# The env var alone can be overridden by an externally-forced platform
# (e.g. a site-installed TPU plugin exporting JAX_PLATFORMS); the config
# update wins regardless, as long as it happens before backend init.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# version-compat shims (jax.shard_map / lax.axis_size on older
# installs) BEFORE any test module runs its `from jax import shard_map`
# — conftest is the one import guaranteed to precede them all
from paddle_tpu.core import jax_compat as _jax_compat  # noqa: E402

_jax_compat.install()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_collection_modifyitems(items):
    # nightly implies slow: a `-m "not slow"` on the command line (the
    # tier-1 gate uses one) REPLACES the addopts' `-m "not nightly"`
    # (pytest keeps only the last -m), which silently pulled the whole
    # compile-heavy nightly sweep into the gate budget.  Dual-marking
    # here keeps the two selections aligned without touching every test.
    for item in items:
        if "nightly" in item.keywords:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as P
    P.seed(0)
    np.random.seed(0)
    yield


@pytest.fixture(autouse=True)
def _lock_order_sanitizer(request):
    # Every chaos-marked test runs under the racelint lock-order
    # tracer: the fault-injection suite doubles as a concurrency
    # stress run, and ANY lock pair observed in both orders fails the
    # gate (a real inversion — the next unlucky schedule deadlocks).
    # PADDLE_TPU_LOCK_TRACE=0 opts out (e.g. when bisecting an
    # unrelated failure).
    if "chaos" not in request.keywords \
            or os.environ.get("PADDLE_TPU_LOCK_TRACE") == "0":
        yield
        return
    from paddle_tpu.analysis.lock_tracer import LockOrderTracer
    with LockOrderTracer() as tracer:
        yield
    snap = tracer.snapshot()
    assert not snap["violations"], (
        f"lock-order inversion observed during chaos run: {snap}")


@pytest.fixture(autouse=True)
def _kv_lifecycle_sanitizer(request, tmp_path_factory):
    # Every chaos-marked test ALSO runs under protolint's KV event
    # tracer: the in-process half patches LocalKVClient (rank-per-
    # thread fleets), and PTPU_KV_TRACE_DIR makes the multiprocess
    # workers (which inherit os.environ through _child_env) append
    # their real-coordination-client streams as kill-safe JSONL the
    # parent collects here.  Any key-lifecycle violation — a get after
    # this process deleted the key, or a double-consume on an
    # exactly-once lane — fails the gate: that is the dynamic
    # double-delivery/stale-read evidence PL101/PL102 police
    # statically.  PADDLE_TPU_KV_TRACE=0 opts out.
    if "chaos" not in request.keywords \
            or os.environ.get("PADDLE_TPU_KV_TRACE") == "0":
        yield
        return
    from paddle_tpu.analysis import kv_tracer
    trace_dir = str(tmp_path_factory.mktemp("kvtrace"))
    prev = os.environ.get("PTPU_KV_TRACE_DIR")
    os.environ["PTPU_KV_TRACE_DIR"] = trace_dir
    try:
        with kv_tracer.KVEventTracer() as tracer:
            yield
        events = tracer.events + kv_tracer.read_trace_dir(trace_dir)
        violations = kv_tracer.lifecycle_violations(events)
        assert not violations, (
            f"KV lifecycle violation observed during chaos run "
            f"({len(events)} events): {violations}")
    finally:
        if prev is None:
            os.environ.pop("PTPU_KV_TRACE_DIR", None)
        else:
            os.environ["PTPU_KV_TRACE_DIR"] = prev
