"""Test env: 8 virtual CPU devices (multi-chip sharding tests run here).

Must set the env BEFORE jax initializes its backends (backend selection is
lazy — first jax.devices() call wins).
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "backend_optimization_level" not in flags:
    # tests are compile-bound, not run-bound: XLA:CPU at -O0 halves the
    # compile time of the deep-model tests with no semantic change
    flags = (flags + " --xla_backend_optimization_level=0").strip()
os.environ["XLA_FLAGS"] = flags

# The env var alone can be overridden by an externally-forced platform
# (e.g. a site-installed TPU plugin exporting JAX_PLATFORMS); the config
# update wins regardless, as long as it happens before backend init.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as P
    P.seed(0)
    np.random.seed(0)
    yield
