"""Mixture-of-Experts: GShard dispatch/combine semantics, gate aux
losses, capacity drops, expert-parallel sharding on the 8-device mesh,
and the global_scatter/global_gather all-to-all primitives.

Reference parity: python/paddle/incubate/distributed/models/moe/ and
python/paddle/distributed/utils/moe_utils.py.
"""
import numpy as np
import pytest

import paddle_tpu as P
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.moe import (GShardGate, MoELayer, NaiveGate,
                                        StackedExpertFFN, SwitchGate,
                                        dispatch_combine)


@pytest.fixture(autouse=True)
def _reset_mesh():
    yield
    mesh_mod.set_mesh(None)


def _np_moe_oracle(x, gate_w, gate_b, w1, b1, w2, b2, top_k):
    """Dense-capacity oracle: every token reaches its top-k experts."""
    n, d = x.shape
    logits = x @ gate_w + gate_b
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    order = np.argsort(-probs, axis=-1, kind="stable")[:, :top_k]
    out = np.zeros_like(x)
    for i in range(n):
        for k in range(top_k):
            e = order[i, k]
            h = np.maximum(x[i] @ w1[e] + b1[e], 0.0)  # relu experts
            out[i] += probs[i, e] * (h @ w2[e] + b2[e])
    return out


class TestDispatchCombine:
    def test_routes_every_token_under_ample_capacity(self):
        rng = np.random.RandomState(0)
        probs = P.to_tensor(
            np.abs(rng.rand(12, 4).astype(np.float32)) + 1e-3)
        probs = probs / probs.sum(axis=-1, keepdim=True)
        combine, dispatch = dispatch_combine(probs, 2, capacity=12)
        d = dispatch.numpy()
        assert d.shape == (12, 4, 12)
        # each token occupies exactly top_k capacity slots
        np.testing.assert_allclose(d.sum(axis=(1, 2)), 2.0)
        # combine carries the top-2 probabilities at the same slots
        c = combine.numpy()
        top2 = -np.sort(-probs.numpy(), axis=-1)[:, :2].sum(-1)
        np.testing.assert_allclose(c.sum(axis=(1, 2)), top2, rtol=1e-6)

    def test_capacity_drops_lowest_priority_tokens(self):
        # all 6 tokens pick expert 0 first; capacity 2 keeps the first 2
        probs = np.full((6, 3), 1e-3, np.float32)
        probs[:, 0] = 0.9
        combine, dispatch = dispatch_combine(P.to_tensor(probs), 1, 2)
        d = dispatch.numpy()
        assert d[:, 0].sum() == 2.0  # expert 0 at capacity
        np.testing.assert_allclose(d.sum(axis=(1, 2))[:2], 1.0)
        np.testing.assert_allclose(d.sum(axis=(1, 2))[2:], 0.0)

    def test_top1_priority_beats_top2(self):
        # token 0 wants E0 as its 2nd choice; tokens 1-2 want E0 first.
        # GShard priority: top-1 claims fill capacity before ANY top-2.
        probs = np.array([[0.4, 0.6, 0.0],
                          [0.9, 0.05, 0.05],
                          [0.9, 0.05, 0.05]], np.float32)
        _, dispatch = dispatch_combine(P.to_tensor(probs), 2, 2)
        d = dispatch.numpy()
        assert d[1, 0].sum() == 1.0 and d[2, 0].sum() == 1.0
        assert d[0, 0].sum() == 0.0  # token 0's 2nd choice lost


class TestMoELayer:
    def test_matches_numpy_oracle_with_relu_experts(self):
        P.seed(0)
        d, dh, E, K, n = 16, 24, 4, 2, 10
        layer = MoELayer(
            d, StackedExpertFFN(E, d, dh, activation="relu"),
            gate={"type": "naive", "top_k": K},
            capacity_factor=(float(n), float(n)))
        rng = np.random.RandomState(1)
        x = rng.randn(2, 5, d).astype(np.float32)
        got = layer(P.to_tensor(x)).numpy().reshape(n, d)

        want = _np_moe_oracle(
            x.reshape(n, d),
            layer.gate.gate.weight.numpy(), layer.gate.gate.bias.numpy(),
            layer.experts.w1.numpy(), layer.experts.b1.numpy(),
            layer.experts.w2.numpy(), layer.experts.b2.numpy(), K)
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)

    def test_layerlist_experts_and_grads(self):
        P.seed(0)
        d = 8
        experts = [P.nn.Linear(d, d) for _ in range(2)]
        layer = MoELayer(d, experts, gate={"type": "naive", "top_k": 1},
                         capacity_factor=(8.0, 8.0))
        x = P.to_tensor(np.random.RandomState(2).randn(4, 2, d)
                        .astype(np.float32))
        y = layer(x)
        assert y.shape == [4, 2, d]
        (y * y).mean().backward()
        for e in experts:
            assert e.weight.grad is not None
            assert np.isfinite(e.weight.grad.numpy()).all()
        assert layer.gate.gate.weight.grad is not None

    def test_gshard_aux_loss_formula(self):
        P.seed(0)
        d, E = 8, 4
        layer = MoELayer(d, StackedExpertFFN(E, d, 8),
                         gate={"type": "gshard", "top_k": 2})
        x = np.random.RandomState(3).randn(4, 2, d).astype(np.float32)
        layer(P.to_tensor(x))
        loss = layer.gate.get_loss()
        assert loss is not None

        xf = x.reshape(-1, d)
        logits = xf @ layer.gate.gate.weight.numpy() \
            + layer.gate.gate.bias.numpy()
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        top1 = probs.argmax(-1)
        c_e = np.bincount(top1, minlength=E) / len(top1)
        m_e = probs.mean(0)
        want = (c_e * m_e).mean() * E * E
        np.testing.assert_allclose(float(loss), want, rtol=1e-5)

    def test_switch_gate_balance_loss_and_eval_determinism(self):
        P.seed(0)
        d, E = 8, 4
        layer = MoELayer(d, StackedExpertFFN(E, d, 16),
                         gate={"type": "switch"})
        x = P.to_tensor(np.random.RandomState(4).randn(5, 2, d)
                        .astype(np.float32))
        layer.train()
        a = layer(x).numpy()
        assert layer.gate.get_loss() is not None
        b = layer(x).numpy()
        assert not np.allclose(a, b), "switch jitter had no effect"
        layer.eval()
        c = layer(x).numpy()
        np.testing.assert_allclose(c, layer(x).numpy())

    def test_capacity_factor_forwarded_to_dict_gates(self):
        layer = MoELayer(8, StackedExpertFFN(2, 8, 8),
                         gate={"type": "gshard", "top_k": 2},
                         capacity_factor=(64.0, 64.0))
        assert layer.capacity_factor == (64.0, 64.0)
        assert layer.gate.capacity_factor == (64.0, 64.0)

    def test_gshard_random_routing_drops_weak_second_choices(self):
        P.seed(0)
        d = 8
        layer = MoELayer(d, StackedExpertFFN(4, d, 8),
                         gate={"type": "gshard", "top_k": 2},
                         capacity_factor=(64.0, 64.0))
        assert layer.gate.random_routing
        x = P.to_tensor(np.random.RandomState(7).randn(8, 4, d)
                        .astype(np.float32))
        layer.train()
        a = layer(x).numpy()
        b = layer(x).numpy()
        assert not np.allclose(a, b), "stochastic routing had no effect"
        layer.eval()  # eval: deterministic, full top-2
        np.testing.assert_allclose(layer(x).numpy(), layer(x).numpy())

    def test_dropped_tokens_fall_back_to_zero(self):
        P.seed(0)
        d = 8
        layer = MoELayer(d, StackedExpertFFN(2, d, 8),
                         gate={"type": "naive", "top_k": 1},
                         capacity_factor=(0.01, 0.01))  # capacity 1
        x = P.to_tensor(np.random.RandomState(5).randn(1, 6, d)
                        .astype(np.float32))
        y = layer(x).numpy()[0]
        # at most top_k * capacity * E = 2 tokens got routed; rest are 0
        nz = (np.abs(y).sum(-1) > 1e-7).sum()
        assert nz <= 2, nz


class TestExpertParallel:
    def test_gpt_moe_ep_sharded_step_matches_single_device(self):
        """GPT with MoE FFNs on a dp2×ep4 mesh == same model on 1 device
        (ample capacity so no routing difference can leak in)."""
        from paddle_tpu.models.gpt import (GPTForCausalLM,
                                           GPTPretrainingCriterion,
                                           gpt3_tiny)

        def one_step(mesh_shape):
            import jax
            from jax.sharding import NamedSharding, PartitionSpec
            if mesh_shape is not None:
                mesh = mesh_mod.init_mesh(mesh_shape)
            else:
                mesh_mod.set_mesh(None)
            P.seed(0)
            cfg = gpt3_tiny(moe_num_experts=4, moe_top_k=2, moe_every=2,
                            moe_capacity_factor=(64.0, 64.0),
                            moe_gate="naive")
            model = GPTForCausalLM(cfg)
            crit = GPTPretrainingCriterion()
            opt = P.optimizer.AdamW(learning_rate=1e-3,
                                    parameters=model.parameters())

            @P.jit.to_static
            def step(ids, labels):
                opt.clear_grad()
                loss = crit(model(ids), labels) \
                    + 0.01 * model.gpt.moe_aux_loss()
                loss.backward()
                opt.step()
                return loss

            rng = np.random.default_rng(0)
            ids = P.to_tensor(rng.integers(0, cfg.vocab_size, (4, 16)),
                              dtype="int64")
            labels = P.to_tensor(rng.integers(0, cfg.vocab_size, (4, 16)),
                                 dtype="int64")
            if mesh_shape is not None:
                sh = NamedSharding(mesh, PartitionSpec("dp", None))
                ids = P.Tensor(jax.device_put(ids._value, sh))
                labels = P.Tensor(jax.device_put(labels._value, sh))
            return float(step(ids, labels)), float(step(ids, labels))

        single = one_step(None)
        sharded = one_step(dict(dp=2, ep=4))
        assert sharded[1] < sharded[0], "MoE GPT did not train"
        np.testing.assert_allclose(single[0], sharded[0], rtol=2e-4)
        np.testing.assert_allclose(single[1], sharded[1], rtol=2e-3)

    def test_global_scatter_gather_roundtrip_and_semantics(self):
        """global_scatter lands token-chunks on expert owners;
        global_gather is its exact inverse (8-way ep)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec
        from paddle_tpu.distributed.utils.moe_utils import (global_gather,
                                                            global_scatter)
        shard_map = jax.shard_map

        mesh = mesh_mod.init_mesh({"ep": 8})
        E, C, d = 8, 4, 16
        rng = np.random.RandomState(0)
        # x[r] on rank r: tokens rank r routed for all 8 experts
        x = rng.randn(8, E, C, d).astype(np.float32)

        def body(xl):  # xl: [1, E, C, d] local block
            routed = global_scatter(xl[0])        # [E/8=1, 8*C, d]
            back = global_gather(routed)
            return routed[None], back[None]

        xs = jax.device_put(
            x, NamedSharding(mesh, PartitionSpec("ep", None, None, None)))
        routed, back = jax.jit(shard_map(
            body, mesh=mesh.jax_mesh if hasattr(mesh, "jax_mesh") else mesh,
            in_specs=PartitionSpec("ep", None, None, None),
            out_specs=PartitionSpec("ep", None, None, None)))(xs)

        np.testing.assert_allclose(np.asarray(back), x, rtol=1e-6)
        # expert e's owner holds every rank's capacity-C chunk for e
        routed = np.asarray(routed)  # [8, 1, 8*C, d]
        for e in range(E):
            want = x[:, e].reshape(8 * C, d)
            np.testing.assert_allclose(routed[e, 0], want, rtol=1e-6)
