"""Gradient merge (k-step accumulation) + LARS (r5, VERDICT #7).

Reference parity:
distributed/fleet/meta_optimizers/gradient_merge_optimizer.py (k-step
accumulate-then-apply, avg), fluid LarsMomentumOptimizer /
meta_optimizers/lars_optimizer.py (layer-wise trust ratio).
"""
import numpy as np
import pytest

import paddle_tpu as P
import paddle_tpu.nn.functional as F


def _model_and_data(seed=0):
    P.seed(seed)
    model = P.nn.Linear(6, 4)
    rng = np.random.default_rng(seed)
    xs = rng.standard_normal((8, 6)).astype(np.float32)
    ys = rng.standard_normal((8, 4)).astype(np.float32)
    return model, xs, ys


def _loss(model, x, y):
    # sum (not mean) so k microbatches sum to the full batch exactly
    return ((model(x) - y) ** 2).sum()


@pytest.mark.parametrize("inner", ["momentum", "adam"])
def test_merge_k_equals_large_batch(inner):
    """k accumulated microbatch steps == one large-batch step."""
    def make_opt(params):
        if inner == "momentum":
            return P.optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                                        parameters=params)
        return P.optimizer.Adam(learning_rate=0.05, parameters=params)

    # merged: 4 microbatches of 2 through GradientMergeOptimizer(k=4)
    model_m, xs, ys = _model_and_data()
    gm = P.optimizer.GradientMergeOptimizer(
        make_opt(model_m.parameters()), k_steps=4, avg=False)
    for i in range(4):
        gm.clear_grad()
        loss = _loss(model_m, P.to_tensor(xs[2 * i:2 * i + 2]),
                     P.to_tensor(ys[2 * i:2 * i + 2]))
        loss.backward()
        gm.step()

    # oracle: one step on the full batch with the bare inner optimizer
    model_o, _, _ = _model_and_data()
    opt = make_opt(model_o.parameters())
    loss = _loss(model_o, P.to_tensor(xs), P.to_tensor(ys))
    loss.backward()
    opt.step()

    np.testing.assert_allclose(model_m.weight.numpy(),
                               model_o.weight.numpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(model_m.bias.numpy(),
                               model_o.bias.numpy(), rtol=1e-5, atol=1e-6)


def test_merge_no_update_until_fire():
    model, xs, ys = _model_and_data()
    w0 = model.weight.numpy().copy()
    gm = P.optimizer.GradientMergeOptimizer(
        P.optimizer.Momentum(learning_rate=0.1,
                             parameters=model.parameters()),
        k_steps=3)
    for i in range(2):   # below k: params must not move
        gm.clear_grad()
        _loss(model, P.to_tensor(xs[:2]), P.to_tensor(ys[:2])).backward()
        gm.step()
    np.testing.assert_allclose(model.weight.numpy(), w0)
    gm.clear_grad()
    _loss(model, P.to_tensor(xs[:2]), P.to_tensor(ys[:2])).backward()
    gm.step()            # firing step
    assert np.abs(model.weight.numpy() - w0).max() > 0


def test_merge_under_to_static():
    """One compiled step function serves accumulating AND firing steps
    (the where-commit form traces once; no retrace at the k-th step)."""
    model, xs, ys = _model_and_data()
    gm = P.optimizer.GradientMergeOptimizer(
        P.optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                             parameters=model.parameters()),
        k_steps=4, avg=False)

    @P.jit.to_static
    def step(x, y):
        gm.clear_grad()
        loss = _loss(model, x, y)
        loss.backward()
        gm.step()
        return loss

    for i in range(4):
        step(P.to_tensor(xs[2 * i:2 * i + 2]),
             P.to_tensor(ys[2 * i:2 * i + 2]))
    assert len(step._compiled) == 1

    model_o, _, _ = _model_and_data()
    opt = P.optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                               parameters=model_o.parameters())
    _loss(model_o, P.to_tensor(xs), P.to_tensor(ys)).backward()
    opt.step()
    np.testing.assert_allclose(model.weight.numpy(),
                               model_o.weight.numpy(), rtol=1e-5, atol=1e-6)


def test_lars_trust_ratio_oracle():
    """LarsMomentum step vs the reference formula computed in numpy."""
    P.seed(0)
    p = P.create_parameter([4, 3], "float32",
                           default_initializer=P.nn.initializer.Normal())
    opt = P.optimizer.LarsMomentum(learning_rate=0.1, momentum=0.9,
                                   lars_coeff=0.001,
                                   lars_weight_decay=0.0005,
                                   parameters=[p])
    rng = np.random.default_rng(1)
    g = rng.standard_normal((4, 3)).astype(np.float32)
    pv = p.numpy().copy()
    p.grad = P.to_tensor(g)
    opt.step()

    p_norm = np.sqrt((pv * pv).sum())
    g_norm = np.sqrt((g * g).sum())
    wd = 0.0005
    local_lr = 0.1 * 0.001 * p_norm / (g_norm + wd * p_norm)
    v = local_lr * (g + wd * pv)
    np.testing.assert_allclose(p.numpy(), pv - v, rtol=1e-5, atol=1e-7)

    # second step exercises the momentum buffer
    p.clear_grad()
    p.grad = P.to_tensor(g)
    pv1 = p.numpy().copy()
    opt.step()
    p_norm1 = np.sqrt((pv1 * pv1).sum())
    local_lr1 = 0.1 * 0.001 * p_norm1 / (g_norm + wd * p_norm1)
    v1 = 0.9 * v + local_lr1 * (g + wd * pv1)
    np.testing.assert_allclose(p.numpy(), pv1 - v1, rtol=1e-5, atol=1e-7)


@pytest.fixture
def _clean_mesh():
    from paddle_tpu.distributed.mesh import set_mesh
    yield
    set_mesh(None)   # fleet.init installs a global mesh; don't leak it


def test_fleet_strategy_applies_lars_and_merge(_clean_mesh):
    """fleet.distributed_optimizer consumes strategy.lars +
    strategy.gradient_merge (the r4 verdict's 'honest fronts' are now
    real)."""
    from paddle_tpu.distributed import fleet

    strategy = fleet.DistributedStrategy()
    strategy.lars = True
    strategy.lars_configs = {"lars_coeff": 0.002}
    strategy.gradient_merge = True
    strategy.gradient_merge_configs = {"k_steps": 2, "avg": True}
    fleet.init(is_collective=True, strategy=strategy)

    model = P.nn.Linear(4, 2)
    opt = P.optimizer.Momentum(learning_rate=0.1,
                               parameters=model.parameters())
    dist_opt = fleet.distributed_optimizer(opt)
    from paddle_tpu.optimizer.gradient_merge import GradientMergeOptimizer
    assert isinstance(dist_opt, GradientMergeOptimizer)
    assert isinstance(dist_opt._inner, P.optimizer.LarsMomentum)
    assert dist_opt._inner._lars_coeff == 0.002

    x = P.to_tensor(np.ones((2, 4), np.float32))
    y = P.to_tensor(np.zeros((2, 2), np.float32))
    w0 = model.weight.numpy().copy()
    for _ in range(2):
        dist_opt.clear_grad()
        F.mse_loss(model(x), y).backward()
        dist_opt.step()
    assert np.abs(model.weight.numpy() - w0).max() > 0
