"""Dy2Static semantic fuzz: generated nested control-flow programs must
compute the SAME result eagerly (python control flow on concrete
tensors) and compiled (converted select/while_loop under to_static).

Programs are generated deterministically (seeded) from a small grammar:
arithmetic on a carried tensor, tensor-`if` (possibly elif/else,
possibly nested), tensor-bounded `while` with a decreasing guard,
python `for` loops, tensor-conditional `break`/`continue` inside
loops, and guard-clause early `return`s — the constructs the converter
owns (r5 grew the exit statements alongside the desugar pre-passes).
"""
import numpy as np
import pytest

import paddle_tpu as p
from paddle_tpu.jit.dy2static import convert_to_static

_OPS = ["y = y * 1.5 + 0.1", "y = y - 0.3", "y = (y * y) * 0.1",
        "y = y / 2.0 + x", "y = y + x * 0.5", "y = _helper(y)",
        "y = y + _helper(x)"]
_CONDS = ["y.sum() > {t}", "y.mean() > {t}", "y.max() < {t}",
          "(y.sum() > {t}) and (y.max() < 50.0)",
          "(y.min() > {t}) or (y.sum() > 0)"]


def _gen_block(rng, depth, lines, indent, in_loop=False):
    pad = "    " * indent
    for _ in range(rng.integers(1, 3)):
        lines.append(pad + _OPS[rng.integers(0, len(_OPS))])
    if in_loop and rng.integers(0, 3) == 0:
        # tensor-conditional loop exit: the r5 desugar turns these into
        # guard flags; eager python takes the real break/continue
        t = float(rng.uniform(-2, 2))
        exit_kw = "break" if rng.integers(0, 2) else "continue"
        lines.append(pad + f"if y.sum() > {t}:")
        lines.append(pad + f"    {exit_kw}")
    kind = rng.integers(0, 4 if depth > 0 else 2)
    if kind == 2 and depth > 0:          # tensor if / elif / else
        t = float(rng.uniform(-2, 2))
        lines.append(pad + "if " + _CONDS[rng.integers(
            0, len(_CONDS))].format(t=t) + ":")
        _gen_block(rng, depth - 1, lines, indent + 1, in_loop)
        if rng.integers(0, 2):
            lines.append(pad + f"elif y.sum() > {t - 1.0}:")
            _gen_block(rng, depth - 1, lines, indent + 1, in_loop)
        lines.append(pad + "else:")
        _gen_block(rng, depth - 1, lines, indent + 1, in_loop)
    elif kind == 3 and depth > 0:        # bounded tensor while
        # one counter PER NESTING DEPTH: a nested while that reset the
        # shared `n` undid the outer loop's progress and produced a
        # genuinely non-terminating program (found at seed 50 — eager
        # and compiled both spin, so it is a generator bug, not a
        # converter bug). The counter increments FIRST so a generated
        # `continue` cannot skip it (termination stays guaranteed).
        n = f"n{indent}"
        lines.append(pad + f"{n} = p.zeros([])")
        lines.append(pad + f"while ({n} < {int(rng.integers(1, 4))}.0)"
                           f" and (y.abs().max() < 100.0):")
        lines.append(pad + f"    {n} = {n} + 1.0")
        _gen_block(rng, depth - 1, lines, indent + 1, in_loop=True)
    elif kind == 1:                      # python for
        lines.append(pad + f"for _k in range({int(rng.integers(2, 4))}):")
        _gen_block(rng, max(depth - 1, 0), lines, indent + 1, in_loop=True)
    # kind == 0: plain arithmetic only


def _make_program(seed, depth=2):
    rng = np.random.default_rng(seed)
    lines = ["def _helper(v):",
             "    if v.mean() > 0.2:",
             "        return v * 0.9",
             "    else:",
             "        return v * 1.1",
             "",
             "def prog(x):", "    y = x * 1.0"]
    if rng.integers(0, 3) == 0:
        # guard-clause early return (r5 return normalization): the rest
        # of the program body becomes the implicit else
        lines.append(f"    if y.sum() > {float(rng.uniform(-1, 1))}:")
        lines.append("        return y * 2.0 + 0.25")
    _gen_block(rng, depth, lines, 1)
    lines.append("    return y")
    src = "\n".join(lines) + "\n"
    ns = {"p": p}
    fname = f"<fuzz_{seed}>"
    # make the source retrievable: inspect.getsource consults linecache
    # by co_filename, which is how the AST converter reads the program
    import linecache
    linecache.cache[fname] = (len(src), None, src.splitlines(True), fname)
    exec(compile(src, fname, "exec"), ns)
    return ns["prog"], src


# seed 2 generates a nesting pattern whose XLA:CPU compile alone takes
# ~5 minutes — that one case IS the exhaustive-compile class pytest.ini
# reserves for the nightly sweep, so it carries the marker (the other
# seeds stay in the default <5-minute gate)
@pytest.mark.parametrize(
    "seed",
    [pytest.param(s, marks=pytest.mark.nightly) if s == 2 else s
     for s in range(16)])
def test_generated_program_eager_vs_compiled(seed):
    prog, src = _make_program(seed)
    rng = np.random.default_rng(seed + 1000)
    compiled = p.jit.to_static(prog)             # one conversion+compile;
    for trial in range(3):                       # trials hit the cache
        x = rng.standard_normal(4).astype(np.float32)
        want = prog(p.to_tensor(x)).numpy()      # eager: python control flow
        got = compiled(p.to_tensor(x)).numpy()   # converted + compiled
        # a generated squaring chain can legitimately overflow — the
        # property is eager == compiled INCLUDING divergence (inf must
        # match inf, elementwise)
        np.testing.assert_allclose(
            got, want, rtol=1e-5, atol=1e-5, equal_nan=True,
            err_msg=f"seed {seed} trial {trial}\n{src}")


@pytest.mark.nightly  # broader sweep of the same property
@pytest.mark.parametrize("seed", list(range(16, 32)))
def test_generated_program_eager_vs_compiled_nightly(seed):
    test_generated_program_eager_vs_compiled(seed)


@pytest.mark.nightly  # depth-3 nesting: while-in-if-in-while class shapes
@pytest.mark.parametrize("seed", list(range(300, 308)))
def test_generated_program_depth3_nightly(seed):
    prog, src = _make_program(seed, depth=3)
    rng = np.random.default_rng(seed + 1000)
    compiled = p.jit.to_static(prog)
    for trial in range(2):
        x = rng.standard_normal(4).astype(np.float32)
        want = prog(p.to_tensor(x)).numpy()
        got = compiled(p.to_tensor(x)).numpy()
        np.testing.assert_allclose(
            got, want, rtol=1e-5, atol=1e-5, equal_nan=True,
            err_msg=f"seed {seed} trial {trial}\n{src}")
