"""Quantized all-reduce (distributed/quantized_collective.py — the
EQuARX-class int8-payload gradient sync; see PAPERS.md)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.distributed.quantized_collective import (
    quantized_all_reduce_mean, quantized_all_reduce_sum)


def _mesh():
    return Mesh(np.array(jax.devices()).reshape(8), ("dp",))


def test_sum_matches_fp32_within_quantization_error():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 64, 128)).astype(np.float32) * 0.01

    fn = jax.jit(shard_map(
        lambda v: quantized_all_reduce_sum(v, "dp"),
        mesh=_mesh(), in_specs=P("dp"), out_specs=P("dp"),
        check_vma=False))
    got = np.asarray(fn(jnp.asarray(x)))
    want = x.sum(0, keepdims=True)  # every shard returns the same sum
    # per-element error bound: 8 ranks x (scale/qmax)/2 rounding error
    scale = np.abs(x).max()
    bound = 8 * scale / 127.0
    assert np.abs(got[0] - want[0]).max() <= bound
    # all shards agree exactly (same integer sum, same scale)
    for i in range(1, 8):
        np.testing.assert_array_equal(got[i], got[0])


def test_mean_tracks_gradient_sync():
    rng = np.random.default_rng(1)
    g = rng.standard_normal((8, 32, 32)).astype(np.float32)

    fn = jax.jit(shard_map(
        lambda v: quantized_all_reduce_mean(
            v, "dp", key=jax.random.PRNGKey(7)),   # stochastic rounding
        mesh=_mesh(), in_specs=P("dp"), out_specs=P("dp"),
        check_vma=False))
    got = np.asarray(fn(jnp.asarray(g)))[0]
    want = g.mean(0)
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 0.05, rel


def test_bits_tradeoff():
    rng = np.random.default_rng(2)
    g = rng.standard_normal((8, 64)).astype(np.float32)

    def err(bits):
        fn = jax.jit(shard_map(
            lambda v: quantized_all_reduce_mean(v, "dp", bits=bits),
            mesh=_mesh(), in_specs=P("dp"), out_specs=P("dp"),
            check_vma=False))
        got = np.asarray(fn(jnp.asarray(g)))[0]
        return np.abs(got - g.mean(0)).max()

    assert err(8) < err(4)  # more bits, less error


def test_int32_wire_dtype():
    """The collective's payload is integer (the narrow-wire contract)."""
    mesh = _mesh()
    traced = jax.jit(shard_map(
        lambda v: quantized_all_reduce_sum(v, "dp"),
        mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
        check_vma=False)).lower(
        jax.ShapeDtypeStruct((8, 16), jnp.float32)).as_text()
    import re
    # the all_reduce itself must CONSUME an integer tensor (a regression
    # that dequantizes before the collective would still leave i32
    # converts elsewhere in the module); the op's type signature spans
    # lines, hence DOTALL over a short window after the op name
    m = re.search(r'all_reduce.{0,600}?tensor<[0-9x]+xi32>', traced,
                  re.S)
    assert m, traced[:2000]


def test_dataparallel_int8_sync_inside_shard_map():
    """DataParallel(comm_dtype='int8')'s eager sync helper: quantized
    mean over the dp axis matches the fp32 mean within the bound."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed.parallel import _int8_grad_sync

    rng = np.random.default_rng(4)
    g = rng.standard_normal((8, 16)).astype(np.float32) * 0.01

    def body(v):
        t = Tensor(v)
        with dist.collective_axis("dp"):
            _int8_grad_sync(t, dist.new_group(axis="dp"), 8)
        return t._value

    out = shard_map(body, mesh=_mesh(), in_specs=P("dp"),
                    out_specs=P("dp"), check_vma=False)(jnp.asarray(g))
    want = g.mean(0)
    got = np.asarray(out)[0]
    bound = np.abs(g).max() / 127.0 * 1.01
    assert np.abs(got - want).max() <= bound
