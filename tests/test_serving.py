"""paddle_tpu.serving — continuous-batching engine, scheduler policy,
traced sampler, metrics, and the bounded-recompile contract.

The e2e tests drive the REAL engine (tiny GPT, compiled prefill/decode)
on the CPU mesh; scheduler/sampler/metrics units run without compiling
anything.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as P
from paddle_tpu import serving
from paddle_tpu.models.gpt import GPTForCausalLM, gpt3_tiny
from paddle_tpu.serving.request import Request, RequestState
from paddle_tpu.serving.sampler import sample_tokens
from paddle_tpu.serving.scheduler import (Scheduler, bucket_for,
                                          default_buckets)

pytestmark = pytest.mark.serving


# ------------------------------------------------------------- bucketing
class TestBucketing:
    def test_bucket_for_picks_smallest_cover(self):
        buckets = (16, 32, 64)
        assert bucket_for(1, buckets) == 16
        assert bucket_for(16, buckets) == 16
        assert bucket_for(17, buckets) == 32
        assert bucket_for(64, buckets) == 64

    def test_bucket_overflow_raises(self):
        with pytest.raises(ValueError, match="exceeds the largest"):
            bucket_for(65, (16, 32, 64))

    def test_default_buckets_cover_max_len(self):
        assert default_buckets(256) == (16, 32, 64, 128, 256)
        assert default_buckets(100) == (16, 32, 64, 100)
        assert default_buckets(8) == (8,)

    def test_compile_bound_declared(self):
        cfg = serving.EngineConfig(max_model_len=64,
                                   prefill_buckets=(16, 32, 64))
        assert cfg.compile_bound == 3 + 3
        assert cfg.compile_bound <= 2 * len(cfg.prefill_buckets)


# ----------------------------------------------------- scheduler policy
def _req(i, prompt_len=4, **sp):
    r = Request(f"r{i}", list(range(1, prompt_len + 1)),
                serving.SamplingParams(**sp) if sp
                else serving.SamplingParams(), arrival_index=i)
    return r


class TestScheduler:
    @pytest.mark.smoke
    def test_fcfs_order_and_head_of_line_blocking(self):
        s = Scheduler(buckets=(16,), page_size=4, growth_reserve_pages=0)
        big = _req(0, prompt_len=16)     # needs 4 pages
        small = _req(1, prompt_len=2)    # needs 1 page
        s.enqueue(big)
        s.enqueue(small)
        # only 2 pages free: the head doesn't fit, and FCFS refuses to
        # let the small one jump the queue
        assert s.pop_admissible(free_slots=4, free_pages=2) is None
        assert s.queue_depth == 2
        # pool grows: head goes first
        assert s.pop_admissible(4, 10) is big
        assert s.pop_admissible(4, 10) is small

    def test_no_free_slot_blocks(self):
        s = Scheduler((16,), 4)
        s.enqueue(_req(0))
        assert s.pop_admissible(free_slots=0, free_pages=100) is None

    def test_page_budget_includes_growth_reserve(self):
        s = Scheduler((16,), page_size=4, growth_reserve_pages=1)
        r = _req(0, prompt_len=8)        # 2 pages + 1 reserve
        assert s.pages_for_prompt(8) == 3
        s.enqueue(r)
        assert s.pop_admissible(1, 2) is None
        assert s.pop_admissible(1, 3) is r

    def test_victim_selection_is_latest_arrival(self):
        s = Scheduler((16,), 4)
        rs = [_req(i) for i in range(3)]
        for r in rs:
            r.state = RequestState.DECODE
        assert s.select_victim(rs) is rs[2]
        # PREFILL-state rows are not preemptible
        rs[2].state = RequestState.PREFILL
        assert s.select_victim(rs) is rs[1]

    def test_requeue_front_keeps_priority(self):
        s = Scheduler((16,), 4)
        a, b = _req(0), _req(1)
        s.enqueue(a)
        s.enqueue(b)
        assert s.pop_admissible(4, 100) is a
        s.requeue_front(a)
        assert s.pop_admissible(4, 100) is a


# ------------------------------------------------------- request states
class TestRequestStateMachine:
    def test_lifecycle_transitions(self):
        r = _req(0)
        r.transition(RequestState.PREFILL)
        r.transition(RequestState.DECODE)
        r.transition(RequestState.EVICTED)
        r.transition(RequestState.PREFILL)
        r.transition(RequestState.DECODE)
        r.transition(RequestState.FINISHED)

    def test_illegal_transition_raises(self):
        r = _req(0)
        with pytest.raises(RuntimeError, match="illegal request"):
            r.transition(RequestState.DECODE)   # waiting -> decode

    def test_replay_tokens_include_generated(self):
        r = _req(0, prompt_len=3)
        r.state = RequestState.DECODE
        r.append_token(7)
        r.append_token(9)
        assert r.replay_token_ids == [1, 2, 3, 7, 9]
        assert r.total_len == 5

    def test_sampling_params_validation(self):
        with pytest.raises(ValueError):
            serving.SamplingParams(max_new_tokens=0)
        with pytest.raises(ValueError):
            serving.SamplingParams(temperature=-1.0)
        with pytest.raises(ValueError):
            serving.SamplingParams(top_p=0.0)


# -------------------------------------------------------------- sampler
class TestSampler:
    def _logits(self, v=16):
        rng = np.random.default_rng(0)
        return jnp.asarray(rng.standard_normal((3, v)).astype(np.float32))

    def _args(self, lg, **kw):
        b = lg.shape[0]
        d = dict(seeds=np.zeros(b, np.int32),
                 positions=np.zeros(b, np.int32),
                 temperatures=np.zeros(b, np.float32),
                 top_ks=np.zeros(b, np.int32),
                 top_ps=np.ones(b, np.float32))
        d.update({k: np.asarray(v) for k, v in kw.items()})
        return (lg, jnp.asarray(d["seeds"]), jnp.asarray(d["positions"]),
                jnp.asarray(d["temperatures"]), jnp.asarray(d["top_ks"]),
                jnp.asarray(d["top_ps"]))

    def test_greedy_is_argmax(self):
        lg = self._logits()
        out = sample_tokens(*self._args(lg))
        np.testing.assert_array_equal(np.asarray(out),
                                      np.argmax(np.asarray(lg), -1))

    def test_seed_and_position_determinism(self):
        lg = self._logits()
        a1 = self._args(lg, temperatures=np.full(3, 0.8, np.float32),
                        seeds=np.array([1, 2, 3], np.int32),
                        positions=np.array([5, 6, 7], np.int32))
        t1 = np.asarray(sample_tokens(*a1))
        t2 = np.asarray(sample_tokens(*a1))
        np.testing.assert_array_equal(t1, t2)
        # different position -> (almost surely) independent draw path;
        # at minimum it must not crash and stays in-vocab
        a2 = self._args(lg, temperatures=np.full(3, 0.8, np.float32),
                        seeds=np.array([1, 2, 3], np.int32),
                        positions=np.array([8, 9, 10], np.int32))
        t3 = np.asarray(sample_tokens(*a2))
        assert ((0 <= t3) & (t3 < 16)).all()

    def test_top_k_restricts_support(self):
        lg = self._logits()
        top2 = np.argsort(np.asarray(lg), -1)[:, -2:]
        for seed in range(8):
            out = np.asarray(sample_tokens(*self._args(
                lg, temperatures=np.full(3, 1.5, np.float32),
                seeds=np.full(3, seed, np.int32),
                top_ks=np.full(3, 2, np.int32))))
            for b in range(3):
                assert out[b] in top2[b]

    def test_top_p_tiny_is_greedy(self):
        lg = self._logits()
        out = np.asarray(sample_tokens(*self._args(
            lg, temperatures=np.full(3, 1.0, np.float32),
            top_ps=np.full(3, 1e-6, np.float32))))
        np.testing.assert_array_equal(out, np.argmax(np.asarray(lg), -1))


# -------------------------------------------------------------- metrics
class TestMetrics:
    def test_snapshot_schema(self):
        m = serving.EngineMetrics()
        m.pages_total = 10
        m.pages_in_use = 5
        m.generated_tokens = 100
        m.ttft.observe(0.25)
        snap = m.snapshot()
        for key in ("requests", "queue_depth", "running", "steps",
                    "tokens", "pages", "compiles", "ttft_ms",
                    "inter_token_ms", "e2e_latency_ms"):
            assert key in snap, key
        assert snap["pages"]["utilization"] == 0.5
        assert snap["ttft_ms"]["p50"] == 250.0
        assert snap["tokens"]["per_s"] > 0

    def test_compile_bound_enforced(self):
        m = serving.EngineMetrics()
        m.compile_bound = 2
        m.note_compile()
        m.note_compile()
        with pytest.raises(RuntimeError, match="recompile storm"):
            m.note_compile()

    def test_histogram_percentiles(self):
        h = serving.Histogram()
        for i in range(1, 101):
            h.observe(i / 1000.0)
        s = h.summary()
        assert s["count"] == 100
        assert s["p50"] == pytest.approx(50.0, abs=2.0)
        assert s["p99"] == pytest.approx(99.0, abs=2.0)


# ------------------------------------------------------- engine (e2e)
@pytest.fixture(scope="module")
def tiny_model():
    P.seed(0)
    return GPTForCausalLM(gpt3_tiny())


def _cfg(**kw):
    d = dict(max_num_seqs=8, page_size=4, max_model_len=48,
             prefill_buckets=(8, 16, 32))
    d.update(kw)
    return serving.EngineConfig(**d)


class TestEngineE2E:
    def test_continuous_batching_token_identical_to_sequential(
            self, tiny_model):
        """Acceptance: >= 8 concurrent mixed-length requests through
        continuous batching produce tokens identical to one-at-a-time
        decode, and the compile counter stays within the declared
        bucket bound."""
        rng = np.random.default_rng(42)
        prompts = [list(rng.integers(1, 256, n))
                   for n in (3, 7, 12, 5, 17, 2, 9, 27)]
        sps = [serving.SamplingParams(
            max_new_tokens=6, temperature=0.7 if i % 2 else 0.0,
            top_k=20 if i % 3 else 0, top_p=0.9 if i % 2 else 1.0,
            seed=i) for i in range(len(prompts))]

        cont = serving.LLMEngine(tiny_model, _cfg())
        batched = cont.generate(prompts, sps)
        assert cont.metrics.compile_count <= \
            2 * len(cont.config.prefill_buckets)
        assert cont.metrics.compile_count <= cont.metrics.compile_bound
        cont.shutdown()

        seq = serving.LLMEngine(tiny_model, _cfg())
        for i, (p, sp) in enumerate(zip(prompts, sps)):
            (one,) = seq.generate([p], [sp])
            assert one.output_token_ids == batched[i].output_token_ids, \
                f"request {i} diverged"
        seq.shutdown()

        assert all(len(r.output_token_ids) == 6 for r in batched)
        snap = cont.metrics.snapshot()
        assert snap["requests"]["finished"] == 8
        assert snap["pages"]["in_use"] == 0          # all freed

    def test_preemption_is_deterministic_and_token_identical(
            self, tiny_model):
        """Pages run out mid-decode: the latest-arrived request is
        evicted, replayed, and still produces the sequential tokens."""
        cfg = _cfg(max_num_seqs=4, max_model_len=16, num_pages=11,
                   prefill_buckets=(8, 16))
        rng = np.random.default_rng(3)
        prompts = [list(rng.integers(1, 256, 3 + i)) for i in range(4)]
        sps = [serving.SamplingParams(max_new_tokens=8, temperature=0.9,
                                      seed=i) for i in range(4)]
        eng = serving.LLMEngine(tiny_model, cfg)
        res = eng.generate(prompts, sps)
        assert eng.metrics.requests_evicted >= 1    # pressure was real
        assert eng.metrics.compile_count <= eng.metrics.compile_bound
        eng.shutdown()

        seq = serving.LLMEngine(tiny_model, cfg)
        for i, (p, sp) in enumerate(zip(prompts, sps)):
            (one,) = seq.generate([p], [sp])
            assert one.output_token_ids == res[i].output_token_ids
        seq.shutdown()

        # determinism of the whole schedule: run the batch again
        eng2 = serving.LLMEngine(tiny_model, cfg)
        res2 = eng2.generate(prompts, sps)
        assert [r.output_token_ids for r in res2] == \
            [r.output_token_ids for r in res]
        assert eng2.metrics.requests_evicted == eng.metrics.requests_evicted
        eng2.shutdown()

    def test_streaming_callbacks_and_step_api(self, tiny_model):
        eng = serving.LLMEngine(tiny_model, _cfg(max_num_seqs=2))
        got = []
        eng.add_request([5, 6, 7],
                        serving.SamplingParams(max_new_tokens=4),
                        stream=lambda r, t, fin: got.append((t, fin)))
        steps = 0
        while eng.has_unfinished():
            events = eng.step()
            steps += 1
            for rid, tok, fin in events:
                assert rid == "req-0"
        assert len(got) == 4
        assert got[-1][1] is True           # finished flag on last token
        assert [f for _, f in got[:-1]] == [False] * 3
        assert steps <= 5
        eng.shutdown()

    def test_eos_stops_early(self, tiny_model):
        eng = serving.LLMEngine(tiny_model, _cfg(max_num_seqs=1))
        # greedy decode from this prompt repeats a token; use the first
        # generated token as eos for a second run -> stops at 1 token
        (probe,) = eng.generate([[9, 8, 7]],
                                serving.SamplingParams(max_new_tokens=3))
        eos = probe.output_token_ids[1]
        (r,) = eng.generate([[9, 8, 7]], serving.SamplingParams(
            max_new_tokens=8, eos_token_id=eos))
        assert r.finish_reason == "stop"
        assert r.output_token_ids[-1] == eos
        assert len(r.output_token_ids) <= 3
        eng.shutdown()

    def test_request_validation(self, tiny_model):
        eng = serving.LLMEngine(tiny_model, _cfg())
        with pytest.raises(ValueError, match="max_model_len"):
            eng.add_request(list(range(1, 40)),
                            serving.SamplingParams(max_new_tokens=20))
        with pytest.raises(ValueError, match="at least one token"):
            eng.add_request([], serving.SamplingParams())
        # worst-case REPLAY length (prompt + max_new - 1) must be
        # bucketable, or an eviction could crash the engine mid-flight:
        # prompt 28 buckets fine at 32, but 28 + 10 - 1 = 37 does not
        with pytest.raises(ValueError, match="largest bucket"):
            eng.add_request(list(range(1, 29)),
                            serving.SamplingParams(max_new_tokens=10))
        eng.shutdown()

    def test_compile_counter_stable_across_reuse(self, tiny_model):
        """Serving many mixed batches must never compile past the
        declared bound (the recompile-storm tripwire)."""
        eng = serving.LLMEngine(tiny_model, _cfg())
        rng = np.random.default_rng(0)
        for round_ in range(3):
            prompts = [list(rng.integers(1, 256, int(n)))
                       for n in rng.integers(2, 30, 5)]
            eng.generate(prompts,
                         serving.SamplingParams(max_new_tokens=3))
        assert eng.metrics.compile_count <= eng.metrics.compile_bound
        snap = eng.metrics.snapshot()
        assert snap["compiles"]["count"] == eng.metrics.compile_count
        eng.shutdown()

    def test_profiler_metrics_report_wiring(self, tiny_model):
        from paddle_tpu import profiler
        eng = serving.LLMEngine(tiny_model, _cfg(max_num_seqs=1),
                                metrics_name="serving.pytest")
        eng.generate([[1, 2, 3]], serving.SamplingParams(max_new_tokens=2))
        rep = profiler.metrics_report()
        assert "serving.pytest" in rep
        assert rep["serving.pytest"]["tokens"]["generated"] == 2
        eng.shutdown()
        assert "serving.pytest" not in profiler.metrics_report()

    def test_predictor_serve_adapter(self, tiny_model):
        from paddle_tpu import inference
        cfg = inference.Config()
        cfg.set_layer(tiny_model)
        eng = inference.create_predictor(cfg).serve(
            max_num_seqs=2, page_size=4, max_model_len=32,
            prefill_buckets=(8, 16))
        (r,) = eng.generate([[3, 1, 4]],
                            serving.SamplingParams(max_new_tokens=2))
        assert len(r.output_token_ids) == 2
        eng.shutdown()


# ------------------------------------------------- CI baseline gates
def test_api_coverage_native_namespace_baseline():
    """The checked-in api_coverage baseline records the paddle_tpu-native
    namespaces (serving, analysis); the current surface must not regress
    against it."""
    import json
    import os
    import sys
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    sys.path.insert(0, tools)
    try:
        import api_coverage
    finally:
        sys.path.remove(tools)
    doc = api_coverage.to_json_doc(api_coverage.collect())
    assert "<native>.serving" in doc["namespaces"]
    with open(os.path.join(tools, "api_coverage_baseline.json"),
              encoding="utf-8") as fh:
        baseline = json.load(fh)
    assert "<native>.serving" in baseline["namespaces"]
    assert api_coverage.diff_regressions(doc, baseline) == []


class TestEngineLifecycleHygiene:
    def test_unadmittable_request_rejected_up_front(self, tiny_model):
        """A request whose admission (pages + growth reserve) can never
        be satisfied even on an empty pool must fail at add_request, not
        deadlock generate() later."""
        cfg = serving.EngineConfig(max_num_seqs=1, page_size=4,
                                   max_model_len=16,
                                   prefill_buckets=(16,))
        eng = serving.LLMEngine(tiny_model, cfg)
        # 4 allocatable pages; prompt 13 needs ceil(13/4)+1 reserve = 5
        with pytest.raises(ValueError, match="growth reserve"):
            eng.add_request(list(range(1, 14)),
                            serving.SamplingParams(max_new_tokens=3))
        # a genuinely servable request still goes through
        (r,) = eng.generate([[1, 2, 3]],
                            serving.SamplingParams(max_new_tokens=2))
        assert len(r.output_token_ids) == 2
        eng.shutdown()

    def test_finished_requests_move_out_of_live_table(self, tiny_model):
        """The live request table must drain as requests finish (a
        perpetual step() loop must not leak one Request per request
        served); finished ones stay inspectable up to the retention
        cap."""
        cfg = _cfg(max_num_seqs=2, finished_retention=3)
        eng = serving.LLMEngine(tiny_model, cfg)
        for i in range(5):
            eng.add_request([1 + i, 2, 3],
                            serving.SamplingParams(max_new_tokens=2))
        while eng.has_unfinished():
            eng.step()
        assert eng._requests == {}
        assert len(eng.finished_requests) == 3      # capped, oldest gone
        assert list(eng.finished_requests) == ["req-2", "req-3", "req-4"]
        # generate() drains its own entries
        eng.generate([[9, 9]], serving.SamplingParams(max_new_tokens=1))
        assert "req-5" not in eng.finished_requests
        eng.shutdown()

    def test_kv_ctx_with_recompute_training_raises(self):
        """Serving a recompute-enabled model left in training mode must
        fail loudly, not silently skip the cache writes."""
        P.seed(0)
        model = GPTForCausalLM(gpt3_tiny(use_recompute=True))
        eng = serving.LLMEngine(model, _cfg(max_num_seqs=1))
        model.train()      # user error after engine init
        with pytest.raises(RuntimeError, match="eval mode"):
            eng.generate([[1, 2, 3]],
                         serving.SamplingParams(max_new_tokens=1))
        model.eval()
        eng.shutdown()

    def test_generate_batch_validation_is_all_or_nothing(self, tiny_model):
        """A bad prompt anywhere in the batch must reject the WHOLE
        generate() call before anything is enqueued — no stranded
        requests silently served and discarded later."""
        eng = serving.LLMEngine(tiny_model, _cfg(max_num_seqs=2))
        with pytest.raises(ValueError, match="max_model_len"):
            eng.generate([[1, 2, 3], list(range(1, 45))],
                         serving.SamplingParams(max_new_tokens=8))
        assert eng.scheduler.queue_depth == 0      # nothing enqueued
        assert eng._requests == {}
        # the engine is unharmed: a clean batch still serves
        (r,) = eng.generate([[1, 2, 3]],
                            serving.SamplingParams(max_new_tokens=2))
        assert len(r.output_token_ids) == 2
        eng.shutdown()
