"""Cross-platform TPU lowering of every Pallas kernel — no chip needed.

`jax.export(..., platforms=["tpu"])` runs the pallas -> Mosaic-dialect
serialization on a CPU-only host: it catches the malformed-grid /
BlockSpec / layout class of errors at the dialect level (the full
Mosaic -> TPU binary compile still needs silicon — tests_tpu/ covers
that), so a kernel that cannot even lower fails HERE, in the gate,
rather than in the first on-silicon run.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import export


def _lower_tpu(fn, *args):
    exp = export.export(jax.jit(fn), platforms=["tpu"])(*args)
    txt = exp.mlir_module()
    assert "tpu_custom_call" in txt, "no Mosaic kernel in the lowering"
    return txt


def _sd(shape, dtype=jnp.bfloat16):
    return jax.ShapeDtypeStruct(shape, dtype)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_fwd_lowers(self, causal):
        from paddle_tpu.ops.pallas.flash_attention import _flash_bhsd

        b, h, s, d = 1, 2, 2048, 128
        _lower_tpu(
            lambda q, k, v: _flash_bhsd(q, k, v, causal, d ** -0.5,
                                        1024, 1024, False),
            _sd((b, h, s, d)), _sd((b, h, s, d)), _sd((b, h, s, d)))

    def test_bwd_lowers(self):
        from paddle_tpu.ops.pallas.flash_attention import _flash_bhsd

        b, h, s, d = 1, 1, 1024, 64

        def f(q, k, v):
            return jnp.sum(_flash_bhsd(q, k, v, True, d ** -0.5, 512,
                                       512, False).astype(jnp.float32))

        _lower_tpu(jax.grad(f, argnums=(0, 1, 2)),
                   _sd((b, h, s, d)), _sd((b, h, s, d)),
                   _sd((b, h, s, d)))

    def test_16k_lowers(self):
        from paddle_tpu.ops.pallas.flash_attention import _flash_bhsd

        b, h, s, d = 1, 1, 16384, 128
        _lower_tpu(
            lambda q, k, v: _flash_bhsd(q, k, v, True, d ** -0.5,
                                        1024, 1024, False),
            _sd((b, h, s, d)), _sd((b, h, s, d)), _sd((b, h, s, d)))


class TestNorms:
    def test_layer_norm_lowers(self):
        from paddle_tpu.ops.pallas.norm import fused_layer_norm

        _lower_tpu(lambda x, w, b: fused_layer_norm(x, w, b, 1e-5, None,
                                                    False),
                   _sd((256, 1024), jnp.float32),
                   _sd((1024,), jnp.float32), _sd((1024,), jnp.float32))

    def test_rms_norm_lowers(self):
        from paddle_tpu.ops.pallas.norm import fused_rms_norm

        _lower_tpu(lambda x, w: fused_rms_norm(x, w, 1e-6, None, False),
                   _sd((256, 1024), jnp.float32),
                   _sd((1024,), jnp.float32))


class TestRingBlocks:
    def test_ring_block_lowers(self):
        from paddle_tpu.ops.pallas.ring_attention import _flash_block

        b, h, s, d = 1, 2, 512, 64

        def f(q, k, v):
            o, lse = _flash_block(q, k, v, True, d ** -0.5, 512, 512,
                                  False)
            return o

        _lower_tpu(f, _sd((b, h, s, d)), _sd((b, h, s, d)),
                   _sd((b, h, s, d)))


class TestBlockSparse:
    def test_fwd_lowers(self):
        from paddle_tpu.ops.pallas.block_sparse_attention import (
            block_sparse_attention, make_sliding_window_mask)

        b, h, s, d = 1, 2, 1024, 64
        bq = bk = 256
        bm = make_sliding_window_mask(s // bq, s // bq, 2, causal=True)
        _lower_tpu(
            lambda q, k, v: block_sparse_attention(
                q, k, v, bm, block_q=bq, block_k=bk, interpret=False),
            _sd((b, h, s, d)), _sd((b, h, s, d)), _sd((b, h, s, d)))

    def test_ragged_tail_lowers(self):
        from paddle_tpu.ops.pallas.block_sparse_attention import (
            block_sparse_attention)

        b, h, s, d = 1, 1, 300, 64
        bm = np.ones((2, 2), bool)
        _lower_tpu(
            lambda q, k, v: block_sparse_attention(
                q, k, v, bm, block_q=256, block_k=256, interpret=False),
            _sd((b, h, s, d), jnp.float32), _sd((b, h, s, d), jnp.float32),
            _sd((b, h, s, d), jnp.float32))
