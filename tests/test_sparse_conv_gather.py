"""True sparse compute for SubmConv3D (r5, VERDICT #5).

Reference: python/paddle/sparse/nn/layer/conv.py + phi sparse
gather-gemm-scatter kernels (the rulebook). Here the rulebook is a
sorted-coordinate join (argsort + searchsorted per kernel offset) and
the gemm is ONE dense [nnz, K³·Cin] x [K³·Cin, Cout] MXU dot — work
scales with nnz, not volume. The dense mirror stays as the oracle.
"""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as P
from paddle_tpu import sparse
import paddle_tpu.sparse.nn as spnn


def _random_sparse(rng, shape, nnz_sites):
    N, D, H, W, C = shape
    dense = np.zeros(shape, np.float32)
    sites = rng.choice(N * D * H * W, size=nnz_sites, replace=False)
    n, z, y, x = np.unravel_index(sites, (N, D, H, W))
    dense[n, z, y, x] = rng.standard_normal((nnz_sites, C))
    return dense


def test_gather_matches_dense_oracle():
    rng = np.random.default_rng(0)
    dense = _random_sparse(rng, (2, 6, 7, 5, 3), 40)
    xt = sparse.to_sparse_coo(P.to_tensor(dense), sparse_dim=4)
    P.seed(0)
    conv = spnn.SubmConv3D(3, 4, kernel_size=3, padding=1)
    assert xt._bcoo.indices.shape[-1] == 4  # fast path engages
    out_g = conv(xt)
    out_d = conv.forward_dense(xt)
    np.testing.assert_allclose(np.asarray(out_g._value),
                               np.asarray(out_d._value),
                               rtol=1e-4, atol=1e-5)


def test_gather_dilation_and_even_kernel():
    rng = np.random.default_rng(1)
    dense = _random_sparse(rng, (1, 8, 8, 8, 2), 30)
    xt = sparse.to_sparse_coo(P.to_tensor(dense), sparse_dim=4)
    conv = spnn.SubmConv3D(2, 3, kernel_size=3, padding=2, dilation=2)
    np.testing.assert_allclose(np.asarray(conv(xt)._value),
                               np.asarray(conv.forward_dense(xt)._value),
                               rtol=1e-4, atol=1e-5)


def test_gather_weight_grads_match_masked_dense():
    rng = np.random.default_rng(2)
    shape = (2, 6, 7, 5, 3)
    dense = _random_sparse(rng, shape, 40)
    xt = sparse.to_sparse_coo(P.to_tensor(dense), sparse_dim=4)
    P.seed(0)
    conv = spnn.SubmConv3D(3, 4, kernel_size=3, padding=1)
    conv(xt).values().sum().backward()
    ge = conv.weight.grad.numpy().copy()
    conv.clear_gradients()
    # oracle: dense conv masked to the active set, summed
    N, D, H, W, C = shape
    active = (dense != 0).any(-1)
    mask = np.broadcast_to(active[:, None],
                           (N, 4, D, H, W)).astype(np.float32)
    out = conv._conv(P.to_tensor(np.moveaxis(dense, -1, 1)))
    (out * P.to_tensor(mask)).sum().backward()
    np.testing.assert_allclose(ge, conv.weight.grad.numpy(),
                               rtol=1e-3, atol=1e-5)


def test_sparse_layer_chain_grads_flow():
    """values() of the gather output stays on the tape: two stacked
    sparse layers backprop into the FIRST layer's weight."""
    rng = np.random.default_rng(3)
    dense = _random_sparse(rng, (1, 6, 6, 6, 3), 25)
    xt = sparse.to_sparse_coo(P.to_tensor(dense), sparse_dim=4)
    P.seed(0)
    c1 = spnn.SubmConv3D(3, 5, kernel_size=3, padding=1)
    c2 = spnn.SubmConv3D(5, 2, kernel_size=3, padding=1)
    c2(c1(xt)).values().sum().backward()
    assert c1.weight.grad is not None
    assert np.abs(c1.weight.grad.numpy()).sum() > 0


def test_compute_scales_with_nnz_not_volume():
    """XLA cost analysis of the compiled gather step: at fixed volume,
    50x the active sites must cost >10x the flops (the dense mirror
    would be occupancy-independent)."""
    rng = np.random.default_rng(4)
    Dv = Hv = Wv = 16
    flops = {}
    for occ in (0.01, 0.5):
        k = int(Dv * Hv * Wv * occ)
        dense = _random_sparse(rng, (1, Dv, Hv, Wv, 8), k)
        xt = sparse.to_sparse_coo(P.to_tensor(dense), sparse_dim=4)
        conv = spnn.SubmConv3D(8, 8, kernel_size=3, padding=1)
        idx = jnp.asarray(xt._bcoo.indices)
        vals = jnp.asarray(xt._bcoo.data)

        def run(vals, w):
            x2 = sparse.SparseCooTensor(jnp.swapaxes(idx, 0, 1), vals,
                                        (1, Dv, Hv, Wv, 8))
            return conv(x2).values()._value

        cost = (jax.jit(run).lower(vals, conv.weight._value)
                .compile().cost_analysis())
        cost = cost[0] if isinstance(cost, (list, tuple)) else cost
        flops[occ] = cost["flops"]
    ratio = flops[0.5] / max(flops[0.01], 1.0)
    assert ratio > 10.0, f"flops ratio {ratio:.1f} — not nnz-scaling"


def test_strided_conv3d_matches_dense_at_stored_sites():
    """Non-submanifold sparse Conv3D (r5): output sites = union of tap
    images (safe static cap), values match the dense conv at every
    stored site, for strided/dilated/anisotropic configs."""
    rng = np.random.default_rng(5)
    shape = (2, 9, 8, 7, 3)
    dense = _random_sparse(rng, shape, 50)
    xt = sparse.to_sparse_coo(P.to_tensor(dense), sparse_dim=4)
    for stride, pad, dil in [(2, 1, 1), (1, 0, 1), (2, 2, 2),
                             ((2, 1, 2), 1, 1)]:
        P.seed(0)
        conv = spnn.Conv3D(3, 5, kernel_size=3, stride=stride,
                           padding=pad, dilation=dil)
        out_s = conv(xt)
        od = conv._conv(
            P.to_tensor(np.moveaxis(dense, -1, 1)))._value
        od = np.moveaxis(np.asarray(od), 1, -1)
        ds = np.asarray(out_s._value)
        assert ds.shape == od.shape
        idx = np.asarray(out_s._bcoo.indices)
        live = np.zeros(od.shape[:4], bool)
        for r in range(idx.shape[0]):
            live[tuple(idx[r])] = True
        np.testing.assert_allclose(ds[live], od[live],
                                   rtol=1e-4, atol=1e-5)


def test_strided_then_subm_chain():
    """Cap-padded strided output feeds SubmConv3D exactly (coalescing
    join + representative-row dedup), with grads into the first conv."""
    rng = np.random.default_rng(6)
    dense = _random_sparse(rng, (2, 9, 8, 7, 3), 50)
    xt = sparse.to_sparse_coo(P.to_tensor(dense), sparse_dim=4)
    P.seed(0)
    c1 = spnn.Conv3D(3, 5, kernel_size=3, stride=2, padding=1)
    c2 = spnn.SubmConv3D(5, 2, kernel_size=3, padding=1)
    out = c1(xt)
    out2 = c2(out)
    oracle = c2.forward_dense(
        sparse.to_sparse_coo(P.to_tensor(np.asarray(out._value))))
    np.testing.assert_allclose(np.asarray(out2._value),
                               np.asarray(oracle._value),
                               rtol=1e-4, atol=1e-5)
    out2.values().sum().backward()
    assert np.abs(c1.weight.grad.numpy()).sum() > 0


def test_conv_bn_relu_subm_stack_with_live_mask():
    """The canonical sparse CNN stack over a cap-padded strided output:
    BatchNorm/Softmax honor the live mask (padded rows neither dilute
    statistics nor leak beta values), ReLU propagates it, and grads
    flow end to end through the taped values."""
    rng = np.random.default_rng(7)
    dense = _random_sparse(rng, (2, 9, 8, 7, 3), 50)
    xt = sparse.to_sparse_coo(P.to_tensor(dense), sparse_dim=4)
    P.seed(0)
    c1 = spnn.Conv3D(3, 5, kernel_size=3, stride=2, padding=1)
    bn = spnn.BatchNorm(5)
    c2 = spnn.SubmConv3D(5, 2, kernel_size=3, padding=1)
    h = c1(xt)
    assert h._live_mask is not None
    h4 = c2(spnn.ReLU()(bn(h)))

    # oracle: dense mirrors with the stored-site mask carried through
    hd = np.asarray(h._value)
    live = (np.abs(hd) > 0).any(-1)
    vl = hd[live]
    mean, var = vl.mean(0), vl.var(0)
    bn_d = (hd - mean) / np.sqrt(var + bn._bn._epsilon)
    bn_d = bn_d * np.asarray(bn._bn.weight.numpy()) + \
        np.asarray(bn._bn.bias.numpy())
    relu_d = np.maximum(np.where(live[..., None], bn_d, 0), 0)
    out_d = c2._conv(P.to_tensor(np.moveaxis(relu_d, -1, 1)))._value
    out_d = np.where(live[..., None],
                     np.moveaxis(np.asarray(out_d), 1, -1), 0)
    np.testing.assert_allclose(np.asarray(h4._value), out_d,
                               rtol=1e-3, atol=1e-4)

    h4.values().sum().backward()
    assert np.abs(c1.weight.grad.numpy()).sum() > 0
    assert np.abs(bn._bn.weight.grad.numpy()).sum() > 0


def test_empty_and_degenerate_inputs():
    empty = sparse.to_sparse_coo(
        P.to_tensor(np.zeros((1, 4, 4, 4, 3), np.float32)), sparse_dim=4)
    assert spnn.SubmConv3D(3, 2, kernel_size=3,
                           padding=1)(empty).nnz() == 0
    assert spnn.Conv3D(3, 2, kernel_size=3, stride=2,
                       padding=1)(empty).nnz() == 0
    # kernel 1 / stride 2 with odd-only coords: no tap lands on the
    # output grid — all-dead mask, in-range coords, zero values
    dd = np.zeros((1, 6, 6, 6, 2), np.float32)
    dd[0, 1, 1, 1] = 1.0
    dd[0, 3, 3, 3] = 2.0
    dd[0, 5, 5, 1] = 3.0
    xt = sparse.to_sparse_coo(P.to_tensor(dd), sparse_dim=4)
    out = spnn.Conv3D(2, 2, kernel_size=1, stride=2, padding=0,
                      bias_attr=False)(xt)
    assert np.asarray(out._value).sum() == 0
    assert not np.asarray(out._live_mask).any()
    assert np.asarray(out._bcoo.indices).max() == 0  # in-range coords


def test_maxpool3d_gather_matches_dense():
    """r5 nnz MaxPool3D (reference sparse/nn/layer/pooling.py): max over
    ACTIVE sites per window via the candidate/join machinery — parity
    with the dense-mirror oracle across kernel/stride/padding configs,
    and grads flow through a sparse conv feeding it."""
    rng = np.random.default_rng(8)
    dense = _random_sparse(rng, (2, 8, 8, 8, 3), 60)
    xt = sparse.to_sparse_coo(P.to_tensor(dense), sparse_dim=4)
    for k, s, pad in [(2, 2, 0), (3, 2, 1), (3, 1, 1)]:
        pool = spnn.MaxPool3D(kernel_size=k, stride=s, padding=pad)
        out_g = pool(xt)
        out_d = pool._forward_dense(
            sparse.to_sparse_coo(P.to_tensor(dense)))
        np.testing.assert_allclose(np.asarray(out_g._value),
                                   np.asarray(out_d._value),
                                   rtol=1e-5, atol=1e-6)
    P.seed(0)
    c1 = spnn.SubmConv3D(3, 3, kernel_size=3, padding=1)
    pool = spnn.MaxPool3D(kernel_size=2, stride=2)
    pool(c1(xt)).values().sum().backward()
    assert np.abs(c1.weight.grad.numpy()).sum() > 0


def test_sparse_nn_layer_submodule_path():
    from paddle_tpu.sparse.nn.layer import (BatchNorm, MaxPool3D,
                                            SubmConv3D, SyncBatchNorm)
    assert MaxPool3D is spnn.MaxPool3D
    assert SyncBatchNorm is spnn.SyncBatchNorm
