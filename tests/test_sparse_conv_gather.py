"""True sparse compute for SubmConv3D (r5, VERDICT #5).

Reference: python/paddle/sparse/nn/layer/conv.py + phi sparse
gather-gemm-scatter kernels (the rulebook). Here the rulebook is a
sorted-coordinate join (argsort + searchsorted per kernel offset) and
the gemm is ONE dense [nnz, K³·Cin] x [K³·Cin, Cout] MXU dot — work
scales with nnz, not volume. The dense mirror stays as the oracle.
"""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as P
from paddle_tpu import sparse
import paddle_tpu.sparse.nn as spnn


def _random_sparse(rng, shape, nnz_sites):
    N, D, H, W, C = shape
    dense = np.zeros(shape, np.float32)
    sites = rng.choice(N * D * H * W, size=nnz_sites, replace=False)
    n, z, y, x = np.unravel_index(sites, (N, D, H, W))
    dense[n, z, y, x] = rng.standard_normal((nnz_sites, C))
    return dense


def test_gather_matches_dense_oracle():
    rng = np.random.default_rng(0)
    dense = _random_sparse(rng, (2, 6, 7, 5, 3), 40)
    xt = sparse.to_sparse_coo(P.to_tensor(dense), sparse_dim=4)
    P.seed(0)
    conv = spnn.SubmConv3D(3, 4, kernel_size=3, padding=1)
    assert xt._bcoo.indices.shape[-1] == 4  # fast path engages
    out_g = conv(xt)
    out_d = conv.forward_dense(xt)
    np.testing.assert_allclose(np.asarray(out_g._value),
                               np.asarray(out_d._value),
                               rtol=1e-4, atol=1e-5)


def test_gather_dilation_and_even_kernel():
    rng = np.random.default_rng(1)
    dense = _random_sparse(rng, (1, 8, 8, 8, 2), 30)
    xt = sparse.to_sparse_coo(P.to_tensor(dense), sparse_dim=4)
    conv = spnn.SubmConv3D(2, 3, kernel_size=3, padding=2, dilation=2)
    np.testing.assert_allclose(np.asarray(conv(xt)._value),
                               np.asarray(conv.forward_dense(xt)._value),
                               rtol=1e-4, atol=1e-5)


def test_gather_weight_grads_match_masked_dense():
    rng = np.random.default_rng(2)
    shape = (2, 6, 7, 5, 3)
    dense = _random_sparse(rng, shape, 40)
    xt = sparse.to_sparse_coo(P.to_tensor(dense), sparse_dim=4)
    P.seed(0)
    conv = spnn.SubmConv3D(3, 4, kernel_size=3, padding=1)
    conv(xt).values().sum().backward()
    ge = conv.weight.grad.numpy().copy()
    conv.clear_gradients()
    # oracle: dense conv masked to the active set, summed
    N, D, H, W, C = shape
    active = (dense != 0).any(-1)
    mask = np.broadcast_to(active[:, None],
                           (N, 4, D, H, W)).astype(np.float32)
    out = conv._conv(P.to_tensor(np.moveaxis(dense, -1, 1)))
    (out * P.to_tensor(mask)).sum().backward()
    np.testing.assert_allclose(ge, conv.weight.grad.numpy(),
                               rtol=1e-3, atol=1e-5)


def test_sparse_layer_chain_grads_flow():
    """values() of the gather output stays on the tape: two stacked
    sparse layers backprop into the FIRST layer's weight."""
    rng = np.random.default_rng(3)
    dense = _random_sparse(rng, (1, 6, 6, 6, 3), 25)
    xt = sparse.to_sparse_coo(P.to_tensor(dense), sparse_dim=4)
    P.seed(0)
    c1 = spnn.SubmConv3D(3, 5, kernel_size=3, padding=1)
    c2 = spnn.SubmConv3D(5, 2, kernel_size=3, padding=1)
    c2(c1(xt)).values().sum().backward()
    assert c1.weight.grad is not None
    assert np.abs(c1.weight.grad.numpy()).sum() > 0


def test_compute_scales_with_nnz_not_volume():
    """XLA cost analysis of the compiled gather step: at fixed volume,
    50x the active sites must cost >10x the flops (the dense mirror
    would be occupancy-independent)."""
    rng = np.random.default_rng(4)
    Dv = Hv = Wv = 16
    flops = {}
    for occ in (0.01, 0.5):
        k = int(Dv * Hv * Wv * occ)
        dense = _random_sparse(rng, (1, Dv, Hv, Wv, 8), k)
        xt = sparse.to_sparse_coo(P.to_tensor(dense), sparse_dim=4)
        conv = spnn.SubmConv3D(8, 8, kernel_size=3, padding=1)
        idx = jnp.asarray(xt._bcoo.indices)
        vals = jnp.asarray(xt._bcoo.data)

        def run(vals, w):
            x2 = sparse.SparseCooTensor(jnp.swapaxes(idx, 0, 1), vals,
                                        (1, Dv, Hv, Wv, 8))
            return conv(x2).values()._value

        cost = (jax.jit(run).lower(vals, conv.weight._value)
                .compile().cost_analysis())
        cost = cost[0] if isinstance(cost, (list, tuple)) else cost
        flops[occ] = cost["flops"]
    ratio = flops[0.5] / max(flops[0.01], 1.0)
    assert ratio > 10.0, f"flops ratio {ratio:.1f} — not nnz-scaling"
