"""Worker script for the fleet-grade fault-tolerance acceptance proof
(tests/test_distributed_multiprocess.py::test_fleet_sigkill_reconfigure_resume).

Launched through ``python -m paddle_tpu.distributed.launch`` as 3 (or,
in baseline mode, 2) OS processes.  Each rank runs a tiny closed-form
linear-regression training loop whose ONLY cross-rank traffic is one
eager ``dist.all_reduce`` (AVG over [loss, grad]) per step — i.e. the
coordination-service collective path the fleet layer bounds.

chaos mode (3 ranks):
  - every rank starts a HeartbeatPublisher + FleetMonitor and installs
    them (the monitor's DEAD verdict aborts blocked collective gets);
  - a quorum DistributedCheckpointer.save fires after ``ckpt_step``
    (replicated: weights; sharded: a per-rank marker array exercising
    reshard-on-shrink);
  - a FaultPlan SIGKILLs rank ``kill_rank`` at the top of step
    ``kill_step`` (site ``fleet.rank_kill`` — a real dead host);
  - survivors catch ``CollectiveTimeout`` naming the dead rank, wait
    for the watchdog's DEAD verdict, ``fleet.reconfigure`` to world
    size 2, reload the step-``ckpt_step`` checkpoint resharded to the
    new world, and re-run steps ``ckpt_step+1 .. total_steps`` —
    recording the resumed loss trajectory.

baseline mode (2 ranks): load the SAME checkpoint directory (written
by the chaos phase at world size 3) at world size 2 and run the same
steps fault-free.  The parent asserts resumed == baseline exactly.

Workers exit via ``os._exit`` — after a peer died, the jax client's
shutdown barrier can never complete, and the test's contract is "no
indefinite hang anywhere on the coordination path".
"""
import json
import os
import sys
import time

import numpy as np

DIM = 4
SHARD_ROWS = 4
LR = 0.05


def batch(step, rank):
    """Deterministic per-(step, fleet-rank) batch — identical between
    the post-reconfigure survivors and the fault-free baseline run."""
    rng = np.random.RandomState(1000 + 17 * step + rank)
    w_true = np.arange(1.0, DIM + 1.0, dtype=np.float64)
    X = rng.randn(8, DIM)
    y = X @ w_true
    return X, y


def train_step(dist, P, w, step, rank):
    """One step: local loss+grad, ONE eager AVG all_reduce over the
    concatenated [loss, grad] vector, SGD update.  Returns (loss, w)."""
    X, y = batch(step, rank)
    err = X @ w - y
    loss = float(np.mean(err * err))
    grad = (2.0 / X.shape[0]) * (X.T @ err)
    vec = P.to_tensor(np.concatenate([[loss], grad]).astype(np.float64))
    dist.all_reduce(vec, op=dist.ReduceOp.AVG)
    out = np.asarray(vec.numpy())
    return float(out[0]), w - LR * out[1:]


def main():
    out_dir, ckpt_dir, mode = sys.argv[1], sys.argv[2], sys.argv[3]
    kill_rank = int(sys.argv[4])
    kill_step = int(sys.argv[5])
    ckpt_step = int(sys.argv[6])
    total_steps = int(sys.argv[7])

    import jax

    import paddle_tpu as P  # noqa: F401  (installs shims)
    from paddle_tpu import distributed as dist
    from paddle_tpu.analysis import kv_tracer
    from paddle_tpu.resilience import faultinject, fleet

    kv_tracer.arm_from_env()   # no-op unless PTPU_KV_TRACE_DIR is set
    grank = jax.process_index()
    from paddle_tpu.observability import fleettrace
    fleettrace.arm_from_env(rank=grank)   # needs PTPU_OBS_SPOOL_DIR
    result = {"mode": mode, "global_rank": grank,
              "launch_world": jax.process_count(), "detection": None,
              "reconfigure_s": None, "reshard_ok": None,
              "losses_resumed": []}

    pub = fleet.install_publisher(fleet.HeartbeatPublisher().start())
    mon = fleet.install_monitor(fleet.FleetMonitor().start())
    ckpt = fleet.DistributedCheckpointer(ckpt_dir, keep=3)

    if mode == "chaos":
        injector = faultinject.FaultInjector(faultinject.FaultPlan(
            [faultinject.FaultSpec("fleet.rank_kill", "rank_kill",
                                   at=kill_step - 1)]
            if grank == kill_rank else [], seed=grank,
            name="fleet-sigkill"))
        faultinject.install(injector)

        w = np.zeros(DIM)
        step = 1
        while step <= total_steps:
            faultinject.fire("fleet.rank_kill", step=step)
            pub.beat()
            try:
                loss, w = train_step(dist, P, w, step, fleet.world().rank)
            except fleet.CollectiveTimeout as exc:
                # ---- detection ----
                result["detection"] = exc.to_dict()
                t0 = time.monotonic()
                # settle until the watchdog verdict covers the missing
                # rank (bounded — the exception may have fired on the
                # deadline before the DEAD classification landed)
                deadline = time.monotonic() + 30.0
                while (exc.missing_rank not in mon.dead_ranks()
                       and time.monotonic() < deadline):
                    time.sleep(0.1)
                dead = mon.dead_ranks() or [exc.missing_rank]
                # ---- reconfigure ----
                new_wv = fleet.reconfigure(dead)
                result["reconfigure_s"] = round(
                    time.monotonic() - t0, 3)
                result["new_world"] = new_wv.to_dict()
                # ---- reload last-good + resume ----
                got = ckpt.load(step=ckpt_step)
                assert got is not None, "no restorable quorum ckpt"
                _, state = got
                w = np.asarray(state["replicated"]["w"])
                marker = np.asarray(state["sharded"]["marker"])
                want = np.sort(np.concatenate(
                    [np.full(SHARD_ROWS, m, np.int64)
                     for m in range(3)]))
                per = want.size // new_wv.size
                mine = want[new_wv.rank * per:(new_wv.rank + 1) * per]
                result["reshard_ok"] = bool(
                    np.array_equal(marker, mine))
                result["losses_resumed"] = []
                step = ckpt_step + 1
                continue
            if step > ckpt_step:
                result["losses_resumed"].append(loss)
            if step == ckpt_step:
                ckpt.save(step, sharded={
                    "marker": np.full(SHARD_ROWS, grank, np.int64)},
                    replicated={"w": w, "step": step})
            step += 1
        result["final_world"] = fleet.world().to_dict()
    else:  # baseline: fault-free world-size-2 resume from the quorum ckpt
        got = ckpt.load(step=ckpt_step, world_size=2, rank=grank)
        assert got is not None, "baseline found no quorum ckpt"
        _, state = got
        w = np.asarray(state["replicated"]["w"])
        for step in range(ckpt_step + 1, total_steps + 1):
            pub.beat()
            loss, w = train_step(dist, P, w, step, grank)
            result["losses_resumed"].append(loss)
        result["final_world"] = fleet.world().to_dict()

    path = os.path.join(out_dir, f"{mode}-rank{grank}.json")
    with open(path + ".tmp", "w") as fh:
        json.dump(result, fh)
    os.replace(path + ".tmp", path)
    # check-out barrier: the coordinator host (global rank 0) must not
    # exit — taking the KV service with it — while a peer is still
    # writing results; then exit WITHOUT the jax shutdown barrier,
    # which can never complete once a peer has died
    fleet.finalize()
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)


if __name__ == "__main__":
    main()
